"""Multi-host pool benchmark — translation of ``benchmarks/k8s_ray_pool.py``.

The reference joins an existing Ray cluster with ``ray.init(address='auto')``
(``k8s_ray_pool.py:90``) from the head pod.  The TPU-native equivalent is a
multi-controller JAX program: EVERY host runs this script,
``jax.distributed.initialize`` discovers the slice (or takes explicit
coordinator flags), and the mesh spans all hosts' devices with sharding
transfers riding ICI/DCN.  Process 0 reports timings and writes result
pickles in the reference format.

Run on each host (TPU pod slices auto-discover; elsewhere pass flags):

    python benchmarks/multihost_pool.py -b 32 -w 32 \
        --coordinator 10.0.0.1:1234 --num_processes 4 --process_id $RANK

One explainer is reused across batch-size settings by mutating the
dispatcher's ``batch_size`` (the reference does the same via
``explainer._explainer.batch_size``, ``k8s_ray_pool.py:74``).
"""

import argparse
import logging
import os
import pickle
import sys
from timeit import default_timer as timer

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedkernelshap_tpu.parallel.mesh import initialize_multihost  # noqa: E402
from benchmarks._common import add_platform_flag, apply_platform  # noqa: E402
from distributedkernelshap_tpu.utils import get_filename, load_data, load_model  # noqa: E402

logging.basicConfig(level=logging.INFO)


def main():
    import jax

    initialize_multihost(args.coordinator, args.num_processes, args.process_id)
    is_lead = jax.process_index() == 0

    data = load_data()
    predictor = load_model()
    X_explain = data['all']['X']['processed']['test'].toarray()
    if args.limit:
        X_explain = X_explain[:args.limit]

    from benchmarks.pool import fit_kernel_shap_explainer

    workers = args.workers if args.workers > 0 else len(jax.devices())
    explainer = fit_kernel_shap_explainer(
        predictor, data, {'batch_size': None, 'n_devices': workers,
                          'coalition_parallel': args.coalition_parallel})
    explainer.explain(X_explain[:8 * workers], silent=True)  # warmup compile

    nruns = args.nruns if args.benchmark else 1
    if is_lead and not os.path.exists('./results'):
        os.mkdir('./results')

    for batch_size in [int(b) for b in args.batch]:
        # reuse the fitted explainer across batch sizes (reference pattern)
        explainer._explainer.batch_size = batch_size
        result = {'t_elapsed': []}
        for run in range(nruns):
            t_start = timer()
            explainer.explain(X_explain, silent=True)
            t_elapsed = timer() - t_start
            if is_lead:
                logging.info("run %d batch %d: %.3fs", run, batch_size, t_elapsed)
                result['t_elapsed'].append(t_elapsed)
                with open(get_filename(workers, batch_size, serve=False), 'wb') as f:
                    pickle.dump(result, f)


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    parser.add_argument("-b", "--batch", nargs='+', required=True)
    parser.add_argument("-w", "--workers", default=-1, type=int,
                        help="Global device count to use; -1 = all visible.")
    parser.add_argument("-benchmark", default=0, type=int)
    parser.add_argument("-n", "--nruns", default=5, type=int)
    parser.add_argument("--coordinator", default=None, type=str,
                        help="coordinator host:port (omit on TPU pods)")
    parser.add_argument("--num_processes", default=None, type=int)
    parser.add_argument("--process_id", default=None, type=int)
    parser.add_argument("--limit", default=0, type=int,
                        help="Explain only the first N instances (0 = all); "
                             "used by the multi-process smoke test.")
    parser.add_argument("--coalition_parallel", default=1, type=int,
                        help="Devices per data-parallel group co-operating "
                             "on one batch via coalition-axis sharding "
                             "(psum'd normal equations over ICI/DCN).")
    add_platform_flag(parser)
    args = parser.parse_args()
    apply_platform(args)
    main()
