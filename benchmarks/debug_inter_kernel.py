"""Standalone Mosaic repro for ``exact_tree_inter`` (the one kernel the
remote compile helper rejected in the 2026-08-02 on-chip A/B, while
``exact_tree_phi`` compiled and ran — ``results/exact_ab.jsonl``).

Calls the kernel directly with ``interpret=False`` on synthetic tensors at
the Adult-GBT shapes so the full compiler error propagates instead of being
swallowed by the engine's auto-degrade (``kernel_shap.py`` Mosaic-rejection
path).  ``--phi`` runs the known-good main-effect kernel first as a
control.  Shapes default to the A/B's (B=256, M=12, K=1, N=100, P=1536,
dmax=32); override to bisect which dimension trips the compiler.
"""

import argparse
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--B", type=int, default=256)
    p.add_argument("--M", type=int, default=12)
    p.add_argument("--K", type=int, default=1)
    p.add_argument("--N", type=int, default=100)
    p.add_argument("--P", type=int, default=1536)
    p.add_argument("--dmax", type=int, default=32)
    p.add_argument("--phi", action="store_true",
                   help="run the known-good exact_tree_phi control first")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributedkernelshap_tpu.ops.pallas_kernels import (
        exact_tree_inter,
        exact_tree_phi,
    )

    print("backend:", jax.default_backend(), jax.devices(), flush=True)
    B, M, K, N, P, dmax = args.B, args.M, args.K, args.N, args.P, args.dmax
    rng = np.random.default_rng(0)
    xo = jnp.asarray(rng.random((B, P, M)) < 0.1, jnp.float32)
    xn = jnp.asarray(rng.random((B, P, M)) < 0.1, jnp.float32)
    zo = jnp.asarray(rng.random((N, P, M)) < 0.5, jnp.float32)
    zd = jnp.asarray(rng.random((N, P)) < 0.05, jnp.float32)
    lv = jnp.asarray(rng.standard_normal((P, K)), jnp.float32)
    bgw = jnp.full((N,), 1.0 / N, jnp.float32)

    if args.phi:
        t0 = time.perf_counter()
        out = exact_tree_phi(xo, xn, zo, zd, lv, bgw, dmax=dmax,
                             interpret=False)
        out.block_until_ready()
        print(f"phi control OK {time.perf_counter() - t0:.2f}s "
              f"out={out.shape}", flush=True)

    t0 = time.perf_counter()
    try:
        out = exact_tree_inter(xo, xn, zo, zd, lv, bgw, dmax=dmax,
                               interpret=False)
        out.block_until_ready()
    except Exception:
        print(f"inter FAILED after {time.perf_counter() - t0:.2f}s",
              flush=True)
        traceback.print_exc()
        return 1
    print(f"inter OK {time.perf_counter() - t0:.2f}s out={out.shape}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
