#!/usr/bin/env bash
# Sweep the serving benchmark across client widths x batch sizes (reference
# benchmarks/k8s_benchmark_serve.sh swept replicas x {1,5,10}).
# Usage: bash tpu_benchmark_serve.sh START END
set -euo pipefail
START=${1:?usage: tpu_benchmark_serve.sh START END}
END=${2:?usage: tpu_benchmark_serve.sh START END}
for replicas in $(seq "$START" "$END"); do
    for batch in 1 5 10; do
        echo "=== replicas=$replicas max_batch_size=$batch ==="
        python benchmarks/serve_explanations.py -r "$replicas" -b "$batch" -n 5
    done
done
