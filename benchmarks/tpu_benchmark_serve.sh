#!/usr/bin/env bash
# Sweep the serving benchmark across pipeline depths x batch sizes
# (reference benchmarks/k8s_benchmark_serve.sh swept replicas x {1,5,10}).
#
# Local mode (default) serves from this host's devices. Cluster mode
# (MODE=cluster) mirrors the reference loop against cluster/Makefile.serve:
# deploy / upload-script / run-experiment / pull-results / destroy per
# configuration.
#
# Usage: bash tpu_benchmark_serve.sh START END
#        MODE=cluster bash tpu_benchmark_serve.sh START END
set -euo pipefail
START=${1:?usage: [MODE=cluster] tpu_benchmark_serve.sh START END}
END=${2:?usage: [MODE=cluster] tpu_benchmark_serve.sh START END}
MODE=${MODE:-local}
MAKEFILE_DIR=$(dirname "$0")/../cluster

for replicas in $(seq "$START" "$END"); do
    for batch in 1 5 10; do
        echo "=== replicas=$replicas max_batch_size=$batch ==="
        if [ "$MODE" = cluster ]; then
            make -C "$MAKEFILE_DIR" -f Makefile.serve deploy
            make -C "$MAKEFILE_DIR" -f Makefile.serve upload-script
            make -C "$MAKEFILE_DIR" -f Makefile.serve run-experiment \
                REPLICAS="$replicas" BATCH="$batch"
            make -C "$MAKEFILE_DIR" -f Makefile.serve pull-results
            make -C "$MAKEFILE_DIR" -f Makefile.serve destroy
        else
            python benchmarks/serve_explanations.py -r "$replicas" -b "$batch" -n 5
        fi
    done
done
