"""One-process TPU measurement session.

Relay operations rule (verify SKILL.md): once a process gets a device
grant, do ALL pending TPU work in that process instead of reconnecting per
task — reconnect churn after a wedge risks re-wedging the relay. This
driver runs the headline bench, the serve sweep and the extra configs in
one session and prints one JSON line per measurement (never killed from
outside: budget its own time instead).

Usage: python benchmarks/tpu_session.py [--serve-batches 1 5 10]
       [--nruns 3] [--skip-configs]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def emit(obj) -> None:
    print(json.dumps(obj), flush=True)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--serve-batches", nargs="*", type=int, default=[1, 5, 10])
    parser.add_argument("--nruns", type=int, default=3)
    parser.add_argument("--replicas", type=int, default=8,
                        help="serve pipeline depth")
    parser.add_argument("--skip-configs", action="store_true")
    args = parser.parse_args()

    t_session = time.perf_counter()
    import jax

    emit({"event": "session_start", "devices": len(jax.devices()),
          "backend": jax.default_backend()})

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.utils import load_data, load_model

    data = load_data()
    clf = load_model()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    X = np.ascontiguousarray(
        data["all"]["X"]["processed"]["test"].toarray(), dtype=np.float32)
    bg = data["background"]["X"]["preprocessed"]

    # ---- headline pool task ------------------------------------------- #
    ex = KernelShap(clf.predict_proba, link="logit", feature_names=gn, seed=0)
    ex.fit(bg, group_names=gn, groups=g)
    ex.explain(X, silent=True)  # compile
    times = []
    for _ in range(args.nruns):
        t0 = time.perf_counter()
        explanation = ex.explain(X, silent=True)
        times.append(time.perf_counter() - t0)
    sv = explanation.shap_values
    total = np.stack(sv, 1).sum(-1) + np.asarray(explanation.expected_value)[None, :]
    err = float(np.abs(total - explanation.data["raw"]["raw_prediction"]).max())
    emit({"metric": "adult_2560_bg100_wall_s", "value": round(float(np.median(times)), 4),
          "unit": "s", "vs_baseline": round(125.05 / float(np.median(times)), 1),
          "additivity_err": err})

    # ---- serve sweep (shares the fitted model) ------------------------ #
    import benchmarks.serve_explanations as se

    model = se.build_model(clf, data)
    for batch in args.serve_batches:
        try:
            se.run_config(clf, data, X, replicas=args.replicas,
                          max_batch_size=batch, host="127.0.0.1", port=0,
                          nruns=args.nruns, model=model)
            import pickle

            from distributedkernelshap_tpu.utils import get_filename

            with open(get_filename(args.replicas, batch, serve=True), "rb") as f:
                t = f and pickle.load(f)["t_elapsed"]
            emit({"metric": f"serve_2560_batch{batch}_wall_s",
                  "value": round(float(np.median(t)), 4), "unit": "s",
                  "vs_serve_best": round(115.13 / float(np.median(t)), 1)})
        except Exception as e:  # keep the session going for later configs
            emit({"metric": f"serve_2560_batch{batch}_wall_s", "error": str(e)})

    # ---- extra configs ------------------------------------------------ #
    if not args.skip_configs:
        import benchmarks.configs as cfgs

        for name in ("adult_stress", "mnist", "covertype"):
            try:
                emit(cfgs.CONFIGS[name](smoke=False))
            except Exception as e:
                emit({"metric": name, "error": str(e)})

    emit({"event": "session_done",
          "total_s": round(time.perf_counter() - t_session, 1)})
    return 0


if __name__ == "__main__":
    sys.exit(main())
