"""Anytime-refinement benchmark + CI gate (``make anytime-bench``).

The anytime subsystem's three load-bearing claims, measured end to end
(standalone, CPU backend, exits nonzero on ``--check`` fail):

1. **Resume bit-identity** — a run preempted after round ``k``, exported
   with :meth:`AnytimeRun.export_state` and restored onto a FRESH engine,
   must finish with phi (and reported error) bit-identical to the
   uninterrupted run at the same cumulative nsamples, for every split
   point of the schedule.  This is what makes the scheduler's round
   boundaries true preemption points: requeueing loses nothing.
2. **Calibration honesty** — the engine's calibrated reported error must
   bound the TRUE error (vs exact-TN ground truth) within
   x``ANYTIME_ERR_BOUND`` at >= ``ANYTIME_COVERAGE`` of observed rounds.
   The measurement is ``estimator_accuracy.sweep_anytime`` — the ONE
   definition both gates share, so this bench and ``make accuracy-gate``
   can never drift apart on what "honest" means.
3. **Overload A/B** — an open-loop arrival stream (arrivals never wait
   for completions) at ~2x the measured full-fidelity capacity, every
   request interactive with a real deadline, against the SAME server
   twice: the anytime arm declares an ``X-DKS-Error-Budget`` (plus a few
   streamed-round probes), the control arm takes the classic
   fixed-nsamples path.  Criteria: the anytime arm answers EVERY admitted
   request by its deadline (degraded, never shed-after-admission) and
   each streamed probe's reported error is monotone non-increasing with a
   final frame; the control arm visibly degrades — sheds/expiries or an
   interactive p99 past the deadline.

Self-records ``wall_s``, ``err_at_deadline`` (mean reported error of the
answers the anytime arm actually returned — the degradation depth the
deadline bought) and ``rounds_per_request_p50`` into
``results/perf_history.jsonl`` with ``checks_ok``, so ``make perf-gate``
fails a commit that regresses refinement depth or residual error.

    JAX_PLATFORMS=cpu python benchmarks/anytime_bench.py --check
"""

import argparse
import http.client
import json
import sys
import threading
import time

import numpy as np

REPO_ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO_ROOT)

from benchmarks.estimator_accuracy import (  # noqa: E402
    ANYTIME_COVERAGE,
    ANYTIME_ERR_BOUND,
    ANYTIME_NSAMPLES,
    _monotonic_ish,
    build_anytime_model,
    sweep_anytime,
)
from benchmarks.regression_gate import (  # noqa: E402
    DEFAULT_HISTORY,
    config_fingerprint,
    record_run,
)

#: open-loop arrival rate as a multiple of measured full-fidelity
#: capacity — the regime where the classic path must fall over and the
#: anytime path must degrade instead
OVERLOAD = 2.0
#: per-request deadline (every request interactive)
DEADLINE_MS = 400
#: client-side slack on the deadline criterion: stdlib HTTP connection +
#: thread-spawn overhead rides on top of the server-side answer
DEADLINE_SLACK_S = 0.20
#: declared error budget — far below the schedule's exhaustion error, so
#: every request refines until the deadline or the schedule runs dry
ERROR_BUDGET = "0.001"
#: overload-phase request count and streamed-probe share
N_REQUESTS = 80
STREAM_EVERY = 10

#: overload serving model: M=16 tensor-train at 4 rows/request sizes the
#: full-fidelity request at ~60 ms on CPU (device work dominates the
#: ~1 ms stdlib HTTP overhead) with a round-0 cost ~8x cheaper — real
#: degradation headroom for the anytime arm
SERVE_M = 16
SERVE_RANK = 4
SERVE_BG = 48
SERVE_NSAMPLES = 768
SERVE_ROWS = 4


# --------------------------------------------------------------------- #
# phase 1: resume bit-identity
# --------------------------------------------------------------------- #


def run_resume_phase(seed: int = 0) -> dict:
    """Straight run vs export-after-round-k + restore-on-fresh-engine,
    for every split point: final phi and reported error must be
    bit-identical (``np.array_equal``, not allclose) at the same
    cumulative nsamples."""

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.anytime.engine import AnytimeRun

    pred, bg, X, _ = build_anytime_model(seed)

    def fresh_engine():
        explainer = KernelShap(pred, seed=seed)
        explainer.fit(bg)
        return explainer._explainer

    engine = fresh_engine()
    straight = engine.anytime_begin(X, nsamples=ANYTIME_NSAMPLES)
    final = None
    while not straight.done:
        final = straight.step()

    splits, identical = [], []
    for k in range(1, straight.schedule.n_rounds):
        part = engine.anytime_begin(X, nsamples=ANYTIME_NSAMPLES)
        for _ in range(k):
            part.step()
        snap = part.export_state()
        other = fresh_engine()
        resumed = AnytimeRun.restore(
            other, other._anytime_schedule(ANYTIME_NSAMPLES), snap)
        res = None
        while not resumed.done:
            res = resumed.step()
        splits.append(k)
        identical.append(
            res.cumulative_nsamples == final.cumulative_nsamples
            and np.array_equal(res.phi, final.phi)
            and np.array_equal(res.est_err, final.est_err))
    return {"splits": splits, "identical": identical,
            "rounds": straight.schedule.n_rounds,
            "bit_identical": bool(identical and all(identical))}


# --------------------------------------------------------------------- #
# phase 3: overload A/B
# --------------------------------------------------------------------- #


def build_serving_model(seed: int = 0):
    from distributedkernelshap_tpu.models.tensor_net import (
        TensorTrainPredictor,
    )
    from distributedkernelshap_tpu.serving.wrappers import KernelShapModel

    rng = np.random.default_rng(seed)
    M, r = SERVE_M, SERVE_RANK
    dims = [1] + [r] * (M - 1) + [1]
    scale = 1.0 / np.sqrt(r)
    cores = []
    for i in range(M):
        A = rng.normal(scale=scale, size=(dims[i], dims[i + 1]))
        B = rng.normal(scale=0.3 * scale, size=(dims[i], dims[i + 1]))
        cores.append((A.astype(np.float32), B.astype(np.float32)))
    model = KernelShapModel(
        TensorTrainPredictor(cores),
        rng.normal(size=(SERVE_BG, M)).astype(np.float32),
        {"seed": seed}, {},
        # l1_reg pinned OFF: 'auto' would engage AIC at this sampled
        # fraction and the deployment would not be anytime-eligible
        explain_kwargs={"nsamples": SERVE_NSAMPLES, "l1_reg": False})
    if not model.supports_anytime:
        raise RuntimeError("overload model is not anytime-eligible")
    return model


def _post(host, port, body, headers, timeout):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/explain", body=body,
                     headers={"Content-Type": "application/json",
                              **headers})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def open_loop(server, plan, timeout=120.0):
    """Fire ``plan`` — ``[(t_offset_s, body, headers, tag), ...]`` — on
    schedule, one thread per request (open loop: arrivals never wait for
    completions).  Returns ``[(tag, status, latency_s, payload)]``."""

    results = [None] * len(plan)
    t0 = time.monotonic()

    def fire(i, offset, body, headers, tag):
        delay = t0 + offset - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        sent = time.monotonic()
        try:
            status, payload = _post(server.host, server.port, body,
                                    headers, timeout)
        except OSError:
            status, payload = -1, b""
        results[i] = (tag, status, time.monotonic() - sent, payload)

    threads = [threading.Thread(target=fire, args=(i, *spec), daemon=True)
               for i, spec in enumerate(plan)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout * 2)
    return [r for r in results if r is not None]


def percentile(values, q):
    return float(np.percentile(np.asarray(values), q)) if values else None


def _scrape_metrics(server):
    conn = http.client.HTTPConnection(server.host, server.port, timeout=30)
    try:
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
    finally:
        conn.close()
    out = {}
    for line in text.splitlines():
        if line and not line.startswith("#"):
            name, value = line.rsplit(" ", 1)
            out[name] = float(value)
    return out


def _scrape_debugz(server):
    conn = http.client.HTTPConnection(server.host, server.port, timeout=30)
    try:
        conn.request("GET", "/debugz")
        return json.loads(conn.getresponse().read().decode())
    finally:
        conn.close()


def _metric_sum(metrics, prefix):
    return sum(v for k, v in metrics.items() if k.startswith(prefix))


def build_plan(n_requests, rate_rps, anytime: bool, seed: int = 0):
    from distributedkernelshap_tpu.serving import wire

    rng = np.random.default_rng(seed)
    plan = []
    for i in range(n_requests):
        rows = rng.normal(size=(SERVE_ROWS, SERVE_M)).astype(np.float32)
        body = json.dumps({"array": rows.tolist()}).encode()
        headers = {"X-DKS-Priority": "interactive",
                   "X-DKS-Deadline-Ms": str(DEADLINE_MS)}
        tag = "plain"
        if anytime:
            headers["X-DKS-Error-Budget"] = ERROR_BUDGET
            if i % STREAM_EVERY == STREAM_EVERY // 2:
                # streamed probes ride the same flood: Accept-negotiated
                # round frames, decoded whole-body after the fact
                headers["Accept"] = (f"{wire.STREAM_CONTENT_TYPE}, "
                                     f"{wire.CONTENT_TYPE}")
                tag = "stream"
        plan.append((i / rate_rps, body, headers, tag))
    return plan


def measure_capacity(server, reps: int = 6, seed: int = 99) -> float:
    """Median closed-loop full-fidelity latency (no budget, no deadline):
    the classic path's service time, HTTP overhead included — the honest
    denominator for the overload factor."""

    rng = np.random.default_rng(seed)
    times = []
    for _ in range(reps):
        rows = rng.normal(size=(SERVE_ROWS, SERVE_M)).astype(np.float32)
        body = json.dumps({"array": rows.tolist()}).encode()
        t0 = time.monotonic()
        status, _ = _post(server.host, server.port, body, {}, timeout=60)
        if status != 200:
            raise RuntimeError(f"capacity probe failed: HTTP {status}")
        times.append(time.monotonic() - t0)
    return float(np.median(times))


def _check_stream_payload(payload: bytes) -> dict:
    """Decode one streamed probe's whole body: well-formed final-flagged
    frame sequence with monotone non-increasing reported error."""

    from distributedkernelshap_tpu.serving import wire

    frames = wire.decode_round_frames(payload)
    errs = [float(np.max(np.asarray(f["est_err"]))) for f in frames]
    return {
        "frames": len(frames),
        "final": bool(frames[-1]["final"]),
        "monotone": all(b <= a + 1e-12 for a, b in zip(errs, errs[1:])),
        "final_err": errs[-1],
    }


def run_overload_phase(seed: int = 0) -> dict:
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    deadline_s = DEADLINE_MS / 1000.0
    arms = {}
    service_s = None
    for arm in ("anytime", "control"):
        # a FRESH server per arm: the keep-best cache and the health
        # engine's windows must not leak across arms
        server = ExplainerServer(
            build_serving_model(seed), host="127.0.0.1", port=0,
            max_batch_size=SERVE_ROWS, batch_timeout_s=0.002,
            max_queue_per_class=256, warmup=True).start()
        try:
            # probe EVERY arm's server: the closed-loop classic requests
            # double as end-to-end warmup (the ladder precompiles, but
            # only a served request proves it), so neither arm's flood
            # starts against a cold trace.  The rate comes from the
            # first measurement — both arms must see the SAME arrivals
            measured = measure_capacity(server)
            if service_s is None:
                service_s = measured
            rate = OVERLOAD / service_s
            plan = build_plan(N_REQUESTS, rate, anytime=(arm == "anytime"),
                              seed=seed)
            t0 = time.monotonic()
            results = open_loop(server, plan)
            wall = time.monotonic() - t0
            metrics = _scrape_metrics(server)
            debugz = _scrape_debugz(server)
        finally:
            server.stop()

        ok_lat = [lat for _, s, lat, _ in results if s == 200]
        admitted = [(tag, s, lat, p) for tag, s, lat, p in results
                    if s != 429]
        summary = {
            "wall_s": round(wall, 3),
            "rate_rps": round(rate, 1),
            "n": len(results),
            "ok": len(ok_lat),
            "shed_429": sum(1 for _, s, _, _ in results if s == 429),
            "expired_504": sum(1 for _, s, _, _ in results if s == 504),
            "other": sorted({s for _, s, _, _ in results}
                            - {200, 429, 504}),
            "p50_s": round(percentile(ok_lat, 50), 4) if ok_lat else None,
            "p99_s": round(percentile(ok_lat, 99), 4) if ok_lat else None,
        }
        if arm == "anytime":
            streams = [_check_stream_payload(p) for tag, s, _, p in results
                       if tag == "stream" and s == 200]
            rounds_total = _metric_sum(metrics, "dks_anytime_rounds_total")
            refines = _metric_sum(metrics, "dks_anytime_refines_total")
            err_sum = _metric_sum(metrics, "dks_anytime_final_err_sum")
            err_count = _metric_sum(metrics, "dks_anytime_final_err_count")
            stop_rounds = [e["rounds"] for e in debugz.get("events", [])
                           if e.get("kind") == "refine_stopped"]
            summary.update({
                "admitted": len(admitted),
                "answered_by_deadline": sum(
                    1 for _, s, lat, _ in admitted
                    if s == 200 and lat <= deadline_s + DEADLINE_SLACK_S),
                "streams": streams,
                "rounds_total": int(rounds_total),
                "refines_total": int(refines),
                "err_at_deadline": (err_sum / err_count
                                    if err_count else None),
                # p50 over the flight recorder's refine_stopped events;
                # the ring is bounded, so fall back to the metrics mean
                # if the flood wrapped them out
                "rounds_per_request_p50": (
                    percentile(stop_rounds, 50) if len(stop_rounds) >= 10
                    else (rounds_total / refines if refines else None)),
            })
        arms[arm] = summary
    return {"service_s": round(service_s, 4),
            "deadline_s": deadline_s, **arms}


# --------------------------------------------------------------------- #


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--reps", type=int, default=3,
                        help="calibration-phase batches")
    parser.add_argument("--no-record", action="store_true",
                        help="measure + check without touching the perf "
                             "history")
    parser.add_argument("--check", action="store_true",
                        help="exit 1 unless every criterion holds")
    args = parser.parse_args(argv)

    t_bench = time.monotonic()
    resume = run_resume_phase(seed=args.seed)
    calibration = sweep_anytime(seed=args.seed, reps=args.reps)
    overload = run_overload_phase(seed=args.seed)
    wall_s = time.monotonic() - t_bench

    a, c = overload["anytime"], overload["control"]
    checks = {
        "resume_bit_identical": resume["bit_identical"],
        "calibration_coverage_ok":
            calibration["coverage"] >= ANYTIME_COVERAGE,
        "calibration_monotonic_ish": _monotonic_ish(calibration["errors"]),
        # the tentpole serving claim: under the same ~2x overload the
        # anytime arm degrades fidelity instead of shedding admitted
        # work, while the classic path visibly falls over
        "anytime_answers_admitted_by_deadline":
            a["admitted"] > 0
            and a["answered_by_deadline"] == a["admitted"],
        "anytime_refines": (a["refines_total"] > 0
                            and a["rounds_total"] > a["refines_total"]),
        "anytime_streams_monotone_final":
            len(a["streams"]) > 0
            and all(s["final"] and s["monotone"] for s in a["streams"]),
        "control_degrades":
            (c["shed_429"] + c["expired_504"]) > 0
            or (c["p99_s"] is not None
                and c["p99_s"] > overload["deadline_s"]),
    }
    checks_ok = all(checks.values())

    config = {"bench": "anytime", "M": SERVE_M, "rank": SERVE_RANK,
              "n_bg": SERVE_BG, "nsamples": SERVE_NSAMPLES,
              "rows": SERVE_ROWS, "n_requests": N_REQUESTS,
              "overload": OVERLOAD, "deadline_ms": DEADLINE_MS,
              "error_budget": ERROR_BUDGET,
              "calibration_nsamples": ANYTIME_NSAMPLES,
              "err_bound": ANYTIME_ERR_BOUND, "seed": args.seed}
    metrics = {"wall_s": round(wall_s, 3)}
    if a["err_at_deadline"] is not None:
        metrics["err_at_deadline"] = round(a["err_at_deadline"], 6)
    if a["rounds_per_request_p50"] is not None:
        metrics["rounds_per_request_p50"] = round(
            a["rounds_per_request_p50"], 2)

    if not args.no_record:
        record_run(DEFAULT_HISTORY, "anytime_bench", config, metrics,
                   extra={"checks_ok": checks_ok,
                          "coverage": calibration["coverage"],
                          "resume_splits": resume["splits"],
                          "control_p99_s": c["p99_s"],
                          "control_sheds": c["shed_429"] + c["expired_504"]})

    result = {
        "bench": "anytime_bench",
        "config_fp": config_fingerprint(config),
        "resume": resume,
        "calibration": {
            "coverage": round(calibration["coverage"], 4),
            "n_pairs": calibration["n_pairs"],
            "errors": {str(n): e
                       for n, e in calibration["errors"].items()},
            "reported": {str(n): e
                         for n, e in calibration["reported"].items()},
        },
        "overload": overload,
        "metrics": metrics,
        "checks": checks,
        "checks_ok": checks_ok,
    }
    print(json.dumps(result))
    if args.check and not checks_ok:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
