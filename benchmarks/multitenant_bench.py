"""Multi-tenant gateway benchmark: one fleet, many models, hot-swappable
(standalone, CPU backend, exits nonzero on ``--check`` fail).

Four measured arms, one JSON line (ISSUE 10; ROADMAP item 4, grounded in
ONNXExplainer's format-generic Shapley framework):

1. **ONNX ingest** (run first so its compile events are fresh) — an
   ONNX-style logistic-regression graph is lifted
   (``registry/onnx_lift.py``), auto-classified onto the **linear fast
   path**, registered, and served end-to-end: its warmup-ladder rungs
   must appear in the compile accounting under ITS model namespace
   (``model=<id>@v1`` signatures) and a duplicate request must hit the
   result cache under ITS fingerprint.  Uses the real ``onnx`` package
   when installed, else the framework-free ``GraphSpec`` form of the
   same graph (reported as ``onnx_available``).
2. **Multi-family fleet** — ≥3 model families (linear softmax, lifted
   tree ensemble on the exact-TreeSHAP path, tensor-train on the exact
   contraction path) served CONCURRENTLY by one server, routed by
   ``X-DKS-Model``.  Every response must be bit-identical to a dedicated
   single-model deployment of the same predictor answering the same row.
3. **Hot swap mid-run** — version 2 of the linear tenant registers while
   an open-loop stream is in flight: zero lost answers, every answer
   bit-identical to EITHER v1 or v2 (never a mixture), and requests
   arriving after the swap completes answer v2.
4. **Noisy tenant** — a flooding tenant with a ``TenantQuota`` sheds
   (429 ``tenant_*``) while two victim tenants keep an interactive p99
   under the SLO bound and shed nothing.
5. **Tenant-count sweep** (``--arm sweep``, ISSUE 11) — 1→8 active
   tenants over MIXED engine paths (linear / exact_tree / exact_tn /
   sampled; two content-identical tenants per family at 8, the
   shared-program case), measuring aggregate goodput of cross-tenant
   continuous batching against (a) the single-tenant-per-model ceiling
   (one tenant per family — the dense dispatch the packer restores) and
   (b) the serialized per-model baseline (``shared_batching=False``, the
   PR-10 dispatch) in the SAME run; plus a deterministic shared-parity
   phase pinning per-tenant phi bit-identical to a dedicated deployment
   at the same coalesced shape.

Every measured run self-records into ``results/perf_history.jsonl`` with
``checks_ok`` (+ the model identities in the config fingerprint) so
``make perf-gate`` covers it — the sweep records its own
``multitenant_sweep`` entry, so cross-tenant goodput regressions gate
too.

    JAX_PLATFORMS=cpu python benchmarks/multitenant_bench.py --check
    JAX_PLATFORMS=cpu python benchmarks/multitenant_bench.py --arm sweep --check
"""

import argparse
import json
import sys
import threading
import time

import numpy as np

REPO_ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO_ROOT)

from benchmarks.scheduling_bench import (  # noqa: E402
    open_loop,
    percentile,
    scrape_metrics,
)

D = 6  # feature width shared by the fleet families
ONNX_D = 9  # distinct width for the ONNX arm: its ladder must TRACE fresh


def _payload_data(payload: str):
    return json.loads(payload)["data"]


def _phi_of(payload: str):
    return json.dumps(_payload_data(payload)["shap_values"])


# --------------------------------------------------------------------- #
# model families (each builder is deterministic, so calling it twice
# yields the bit-identical "dedicated deployment" reference)
# --------------------------------------------------------------------- #


def build_linear(seed=1):
    from distributedkernelshap_tpu.models import LinearPredictor
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(seed)
    W = rng.normal(size=(D, 2)).astype(np.float32)
    b = rng.normal(size=(2,)).astype(np.float32)
    bg = np.random.default_rng(100).normal(size=(12, D)).astype(np.float32)
    return BatchKernelShapModel(LinearPredictor(W, b, activation="softmax"),
                                bg, {"link": "logit", "seed": 0}, {})


def build_tree():
    from sklearn.ensemble import HistGradientBoostingRegressor

    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(7)
    X = rng.normal(size=(200, D))
    y = X[:, 0] * 2 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    gbr = HistGradientBoostingRegressor(max_iter=10, max_depth=3,
                                        random_state=0).fit(X, y)
    bg = np.random.default_rng(101).normal(size=(12, D)).astype(np.float32)
    return BatchKernelShapModel(gbr.predict, bg, {"seed": 0}, {})


def build_tt():
    from distributedkernelshap_tpu.models.tensor_net import (
        TensorTrainPredictor,
    )
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(9)
    ranks = [1, 2, 2, 2, 2, 2, 1]
    cores = [(rng.normal(scale=0.5,
                         size=(ranks[i], ranks[i + 1])).astype(np.float32),
              rng.normal(scale=0.5,
                         size=(ranks[i], ranks[i + 1])).astype(np.float32))
             for i in range(D)]
    bg = np.random.default_rng(102).normal(size=(12, D)).astype(np.float32)
    return BatchKernelShapModel(TensorTrainPredictor(cores), bg,
                                {"seed": 0}, {})


FAMILIES = {"lin": build_linear, "tree": build_tree, "tt": build_tt}


def _serve_registry(registry, **kwargs):
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    defaults = dict(host="127.0.0.1", port=0, max_batch_size=8,
                    batch_timeout_s=0.004, pipeline_depth=2)
    defaults.update(kwargs)
    return ExplainerServer(registry=registry, **defaults).start()


def _wait_warm(server, timeout_s: float = 120.0) -> None:
    """Wait out the readiness gate so first-compile time never pollutes
    the measured request latencies (the fleet's real routers hold traffic
    on the warming 503 the same way)."""

    deadline = time.monotonic() + timeout_s
    while server.warmup_status()["state"] in ("pending", "running") \
            and time.monotonic() < deadline:
        time.sleep(0.05)


# --------------------------------------------------------------------- #
# arm 1: ONNX ingest onto the linear fast path, end-to-end
# --------------------------------------------------------------------- #


def _logreg_graph_spec(W: np.ndarray, b: np.ndarray):
    """The logistic-regression graph (Gemm -> Sigmoid), as a real ONNX
    ModelProto when the package is installed (round-tripping through
    serialized bytes, the customer hand-off shape), else as the
    equivalent GraphSpec the same translator consumes."""

    from distributedkernelshap_tpu.registry import (
        GraphSpec,
        NodeSpec,
        lift_graph,
        lift_onnx,
    )

    try:
        import onnx
        from onnx import TensorProto, helper, numpy_helper

        graph = helper.make_graph(
            [helper.make_node("Gemm", ["X", "W", "b"], ["z"]),
             helper.make_node("Sigmoid", ["z"], ["y"])],
            "logreg",
            [helper.make_tensor_value_info(
                "X", TensorProto.FLOAT, [None, W.shape[0]])],
            [helper.make_tensor_value_info(
                "y", TensorProto.FLOAT, [None, 1])],
            initializer=[numpy_helper.from_array(W, "W"),
                         numpy_helper.from_array(b, "b")])
        model = helper.make_model(graph)
        return lift_onnx(model.SerializeToString()), True
    except ImportError:
        spec = GraphSpec(
            nodes=[NodeSpec("Gemm", ("X", "W", "b"), ("z",), {}),
                   NodeSpec("Sigmoid", ("z",), ("y",), {})],
            initializers={"W": W, "b": b},
            input_name="X", output_name="y", input_dim=W.shape[0])
        return lift_graph(spec), False


def run_onnx_arm():
    from distributedkernelshap_tpu.registry import ModelRegistry
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(11)
    W = rng.normal(size=(ONNX_D, 1)).astype(np.float32)
    b = rng.normal(size=(1,)).astype(np.float32)
    pred, onnx_available = _logreg_graph_spec(W, b)

    bg = rng.normal(size=(12, ONNX_D)).astype(np.float32)
    serving = BatchKernelShapModel(pred, bg, {"link": "logit", "seed": 0},
                                  {})
    registry = ModelRegistry()
    rm = registry.register("onnx_lr", serving)
    server = _serve_registry(registry, max_batch_size=4, warmup=True,
                             cache_bytes=1 << 20)
    try:
        # the ladder must finish (and stamp its model=... compile
        # signatures) before the timed requests
        _wait_warm(server, timeout_s=60)
        row = rng.normal(size=(1, ONNX_D)).astype(np.float32)
        results = open_loop(server, [
            (0.0, row, {"X-DKS-Model": "onnx_lr"}, "first"),
            (0.1, row, {"X-DKS-Model": "onnx_lr"}, "dup"),
        ])
        metrics = scrape_metrics(server)
        statuses = sorted(s for _, s, _, _ in results)
        payloads = {tag: p for tag, s, _, p in results if s == 200}
        signed = [name for name in metrics
                  if name.startswith("dks_compile_total")
                  and "model=onnx_lr@v1" in name]
        hits = metrics.get("dks_serve_cache_hits_total", 0)
    finally:
        server.stop()
    # additivity of the served ONNX model (sanity that the lift is real)
    data = _payload_data(payloads.get("first", '{"data": {}}'))
    additive = False
    if data.get("shap_values") is not None:
        total = (np.asarray(data["shap_values"]).sum(-1)
                 + np.asarray(data["expected_value"])[:, None])
        additive = bool(np.allclose(
            total, np.asarray(data["raw"]["raw_prediction"]).T, atol=1e-3))
    return {
        "onnx_available": onnx_available,
        "classified_path": rm.path,
        "statuses": statuses,
        "warmup_state": server.warmup_status()["state"],
        "namespace_signed_compiles": signed[:4],
        "cache_hits": int(hits),
        "dup_bit_identical": (payloads.get("first") == payloads.get("dup")
                              and "first" in payloads),
        "additivity_ok": additive,
        "fingerprint": rm.fingerprint,
    }


# --------------------------------------------------------------------- #
# arm 2: >=3 families served concurrently, phi vs dedicated deployments
# --------------------------------------------------------------------- #


def run_multifamily_arm(requests_per_family=24, rate_rps=60.0, pool=6,
                        seed=0):
    from distributedkernelshap_tpu.registry import ModelRegistry

    registry = ModelRegistry()
    for name, build in FAMILIES.items():
        registry.register(name, build())
    paths = {name: registry.resolve(name).path for name in FAMILIES}

    rng = np.random.default_rng(seed)
    rows = {name: rng.normal(size=(pool, 1, D)).astype(np.float32)
            for name in FAMILIES}
    # dedicated single-model deployments: fresh, separately constructed
    # models from the same deterministic builders — the reference answers
    dedicated = {name: build() for name, build in FAMILIES.items()}
    expected = {}
    for name in FAMILIES:
        for i in range(pool):
            expected[(name, i)] = _phi_of(
                dedicated[name].explain_batch(rows[name][i])[0])

    # max_batch_size=1: the bit-identity claim is that the GATEWAY adds
    # zero numeric perturbation vs a dedicated deployment.  Coalescing
    # changes f32 reduction order at the ~1-ULP level for B>1 batches (a
    # pre-existing engine property, independent of multitenancy), so the
    # parity arm pins every device call to the dedicated deployment's
    # B=1 shape; tenants still interleave concurrently through the
    # scheduler and the pipelined dispatcher.
    server = _serve_registry(registry, max_batch_size=1, warmup=True)
    try:
        _wait_warm(server)
        plan = []
        n = requests_per_family * len(FAMILIES)
        order = [name for name in FAMILIES] * requests_per_family
        for k, name in enumerate(order):
            i = int(rng.integers(pool))
            plan.append((k / rate_rps, rows[name][i],
                         {"X-DKS-Model": name}, (name, i)))
        t0 = time.monotonic()
        results = open_loop(server, plan)
        wall = time.monotonic() - t0
        metrics = scrape_metrics(server)
    finally:
        server.stop()

    ok = [r for r in results if r[1] == 200]
    mismatches = sum(1 for tag, s, _, payload in results
                     if s == 200 and _phi_of(payload) != expected[tag])
    per_model_counts = {
        name: int(metrics.get(
            f'dks_registry_requests_total{{model="{name}"}}', 0))
        for name in FAMILIES}
    return {
        "wall_s": round(wall, 3),
        "n": n,
        "ok": len(ok),
        "goodput_rps": round(len(ok) / wall, 2),
        "paths": paths,
        "phi_mismatches": mismatches,
        "per_model_requests_total": per_model_counts,
        "families_served": sorted(set(tag[0] for tag, s, _, _ in results
                                      if s == 200)),
    }


# --------------------------------------------------------------------- #
# arm 3: hot swap mid-run
# --------------------------------------------------------------------- #


def run_hotswap_arm(n_per_segment=24, rate_rps=40.0, seed=3):
    """Two open-loop segments around a mid-run hot swap.

    Segment A streams against v1 and TRIGGERS the swap after a few
    requests, so v2's ladder warm-up, the atomic flip and v1's drain all
    overlap live traffic (in-flight requests pinned v1 and must finish
    on it).  After the swap thread joins, segment B streams again — by
    then the flip is complete, so every segment-B answer must be
    bit-identical v2.  ``max_batch_size=1`` for the same bit-identity
    reason as the multi-family arm."""

    from distributedkernelshap_tpu.registry import ModelRegistry

    registry = ModelRegistry()
    registry.register("lin", build_linear(seed=1))
    rng = np.random.default_rng(seed)
    row = rng.normal(size=(1, D)).astype(np.float32)
    v1_phi = _phi_of(build_linear(seed=1).explain_batch(row)[0])
    v2_phi = _phi_of(build_linear(seed=2).explain_batch(row)[0])

    server = _serve_registry(registry, cache_bytes=0, max_batch_size=1,
                             warmup=True)
    swap_started = threading.Event()
    swap_done = threading.Event()

    def swap():
        swap_started.wait()
        # the gateway's hot-swap: warm v2's ladder, flip, drain v1 — all
        # while segment A keeps firing
        registry.register("lin", build_linear(seed=2))
        swap_done.set()

    swapper = threading.Thread(target=swap, daemon=True)
    swapper.start()
    try:
        _wait_warm(server)
        plan_a = []
        for k in range(n_per_segment):
            plan_a.append((k / rate_rps, row, {"X-DKS-Model": "lin"}, k))

        def trigger():
            time.sleep((n_per_segment // 4) / rate_rps)
            swap_started.set()

        threading.Thread(target=trigger, daemon=True).start()
        results_a = open_loop(server, plan_a)
        swapper.join(timeout=120)
        overlapped = swap_done.is_set() and any(
            s == 200 for _, s, _, _ in results_a)
        results_b = open_loop(server, [
            (k / rate_rps, row, {"X-DKS-Model": "lin"}, k)
            for k in range(n_per_segment)])
        v1_rm = registry._models["lin"]["versions"][1]
        drained = v1_rm.state == "retired" and v1_rm.inflight == 0
    finally:
        server.stop()

    results = results_a + results_b
    lost = 2 * n_per_segment - sum(1 for _, s, _, _ in results if s == 200)
    wrong = sum(1 for _, s, _, p in results
                if s == 200 and _phi_of(p) not in (v1_phi, v2_phi))
    # every request fired after the swap completed must answer v2 (the
    # flip is atomic at admission; segment-A in-flights may be either)
    post_swap_non_v2 = sum(1 for _, s, _, p in results_b
                           if s == 200 and _phi_of(p) != v2_phi)
    v2_answers = sum(1 for _, s, _, p in results
                     if s == 200 and _phi_of(p) == v2_phi)
    return {
        "n": 2 * n_per_segment,
        "lost": lost,
        "changed_or_mixed": wrong,
        "post_swap_non_v2": post_swap_non_v2,
        "v2_answers": v2_answers,
        "swap_completed": swap_done.is_set(),
        "swap_overlapped_traffic": overlapped,
        "v1_drained_retired": drained,
    }


# --------------------------------------------------------------------- #
# arm 4: noisy tenant vs quota isolation
# --------------------------------------------------------------------- #


def run_noisy_arm(victim_requests=32, flood_requests=120,
                  victim_rate=30.0, flood_rate=150.0, flood_rows=8,
                  slo_p99_s=2.0, seed=4):
    from distributedkernelshap_tpu.registry import ModelRegistry, TenantQuota

    registry = ModelRegistry()
    registry.register("victim_a", build_linear(seed=1))
    registry.register("victim_b", build_tt())
    # the quota's in-flight bound also caps how many flood requests the
    # scheduler can COALESCE into one device batch (same tenant, same
    # engine), so a victim never waits behind an unbounded same-model
    # mega-batch — the queue-bound half of tenant isolation
    registry.register("noisy", build_linear(seed=5),
                      quota=TenantQuota(rate_per_s=5.0, burst=3,
                                        max_inflight=3))
    rng = np.random.default_rng(seed)
    server = _serve_registry(registry, max_queue_per_class=10_000,
                             warmup=True)
    try:
        # warm every tenant's ladder first: the victims' p99 must measure
        # steady-state isolation, not the TT path's first-compile
        _wait_warm(server)
        plan = []
        for k in range(victim_requests):
            for name in ("victim_a", "victim_b"):
                plan.append((k / victim_rate,
                             rng.normal(size=(1, D)).astype(np.float32),
                             {"X-DKS-Model": name,
                              "X-DKS-Priority": "interactive"},
                             name))
        for k in range(flood_requests):
            plan.append((k / flood_rate,
                         rng.normal(size=(flood_rows, D)).astype(
                             np.float32),
                         {"X-DKS-Model": "noisy",
                          "X-DKS-Priority": "interactive"},
                         "noisy"))
        results = open_loop(server, plan)
        metrics = scrape_metrics(server)
    finally:
        server.stop()

    by_tag = {}
    for tag, status, latency, _ in results:
        by_tag.setdefault(tag, []).append((status, latency))
    summary = {}
    for tag, rs in sorted(by_tag.items()):
        lat_ok = [lat for s, lat in rs if s == 200]
        summary[tag] = {
            "n": len(rs), "ok": len(lat_ok),
            "shed_429": sum(1 for s, _ in rs if s == 429),
            "p99_s": round(percentile(lat_ok, 99), 4) if lat_ok else None,
        }
    tenant_sheds = {
        name: sum(v for k, v in metrics.items()
                  if k.startswith("dks_registry_sheds_total")
                  and f'model="{name}"' in k)
        for name in ("victim_a", "victim_b", "noisy")}
    summary["victim_interactive_p99_s"] = max(
        summary["victim_a"]["p99_s"] or 0.0,
        summary["victim_b"]["p99_s"] or 0.0)
    summary["slo_p99_s"] = slo_p99_s
    summary["tenant_sheds"] = {k: int(v) for k, v in tenant_sheds.items()}
    return summary


# --------------------------------------------------------------------- #
# arm 5 (--arm sweep): tenant-count sweep 1->8 over mixed engine paths
# --------------------------------------------------------------------- #


def build_sampled():
    """A generic numpy callable: nothing lifts it, so it classifies (and
    serves) on the SAMPLED masked-EY path — the fourth path of the mixed
    sweep roster."""

    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(13)
    W1 = rng.normal(scale=0.5, size=(D, 8)).astype(np.float32)
    W2 = rng.normal(scale=0.5, size=(8, 1)).astype(np.float32)

    def mlp(x):
        x = np.asarray(x, dtype=np.float32)
        return np.maximum(x @ W1, 0.0) @ W2

    bg = np.random.default_rng(103).normal(size=(12, D)).astype(np.float32)
    return BatchKernelShapModel(mlp, bg, {"seed": 0}, {})


SWEEP_FAMILIES = ("lin", "tree", "tt", "samp")
_SWEEP_BUILDERS = {"lin": lambda: build_linear(seed=1), "tree": build_tree,
                   "tt": build_tt, "samp": build_sampled}
#: models reused across sweep arms so each engine compiles its ladder
#: once; (family, copy) — copies are DISTINCT engines with IDENTICAL
#: content (the shared-program case)
_SWEEP_CACHE = {}


def _sweep_model(family: str, copy: int):
    key = (family, copy)
    if key not in _SWEEP_CACHE:
        _SWEEP_CACHE[key] = _SWEEP_BUILDERS[family]()
    return _SWEEP_CACHE[key]


def _sweep_roster(n_tenants: int):
    """``[(tenant_id, family, model), ...]`` — families round-robin, so 8
    tenants = 2 content-identical tenants per family."""

    return [(f"{SWEEP_FAMILIES[i % len(SWEEP_FAMILIES)]}{i // len(SWEEP_FAMILIES)}",
             SWEEP_FAMILIES[i % len(SWEEP_FAMILIES)],
             _sweep_model(SWEEP_FAMILIES[i % len(SWEEP_FAMILIES)],
                          i // len(SWEEP_FAMILIES)))
            for i in range(n_tenants)]


def _sweep_setup(roster, n_requests: int, rate_rps: float,
                 shared: bool = True, seed: int = 17):
    """Bring up one arm's server (registry + warm ladder + one untimed
    warm pass) and build its open-loop plan.  Measurement happens later,
    interleaved round-robin across ALL arms, so box drift hits every arm
    symmetrically (the streaming/warmup benches' pattern — back-to-back
    identical passes drift ~2x on this 1-core box)."""

    from distributedkernelshap_tpu.registry import ModelRegistry

    registry = ModelRegistry()
    for name, _family, model in roster:
        registry.register(name, model)
    server = _serve_registry(registry, max_batch_size=8,
                             batch_timeout_s=0.008, warmup=True,
                             shared_batching=shared)
    _wait_warm(server, timeout_s=300)
    rng = np.random.default_rng(seed)
    pools = {family: rng.normal(size=(4, 1, D)).astype(np.float32)
             for family in SWEEP_FAMILIES}
    # round-robin over tenants ordered BY FAMILY (lin0, lin1, tree0, ...):
    # every tenant gets the same request share, and a family's
    # content-identical tenants arrive adjacently — the traffic shape
    # shared programs exist for (two tenants of one public base model
    # serving the same user population)
    ordered = sorted(roster, key=lambda r: (r[1], r[0]))
    plan = []
    for k in range(n_requests):
        name, family, _model = ordered[k % len(ordered)]
        plan.append((k / rate_rps, pools[family][k % 4],
                     {"X-DKS-Model": name}, name))
    open_loop(server, plan[:len(roster) * 4])  # first-touch costs, untimed
    return {"server": server, "plan": plan, "roster": roster,
            "shared": shared, "best": None, "lost": False}


def _sweep_measure_pass(arm) -> None:
    """One timed open-loop pass; keeps the arm's best (capacity) pass."""

    t0 = time.monotonic()
    results = open_loop(arm["server"], arm["plan"])
    wall = time.monotonic() - t0
    arm["lost"] = arm["lost"] or len(results) < len(arm["plan"]) or any(
        s != 200 for _, s, _, _ in results)
    if arm["best"] is None or wall < arm["best"][0]:
        arm["best"] = (wall, results)


def _sweep_finish(arm):
    """Tear one arm down and summarise its best pass + dispatch density."""

    server = arm["server"]
    try:
        metrics = scrape_metrics(server)
    finally:
        server.stop()
    wall, results = arm["best"]
    ok = 0 if arm["lost"] else sum(
        1 for _, s, _, _ in results if s == 200)
    cycles = metrics.get("dks_serve_batch_groups_count", 0)
    padded = sum(v for k, v in metrics.items()
                 if k.startswith("dks_serve_padded_rows_total"))
    by_tenant = {}
    for tag, s, _, _ in results:
        by_tenant.setdefault(tag, [0, 0])
        by_tenant[tag][0] += 1
        by_tenant[tag][1] += int(s == 200)
    return {
        "tenants": len(arm["roster"]),
        "shared_batching": arm["shared"],
        "n": len(arm["plan"]),
        "ok": ok,
        "wall_s": round(wall, 3),
        "goodput_rps": round(ok / wall, 2) if wall else None,
        "avg_groups_per_cycle": (round(
            metrics.get("dks_serve_batch_groups_sum", 0.0) / cycles, 2)
            if cycles else None),
        "padded_rows_total": int(padded),
        "per_tenant_ok": {t: f"{okc}/{n}"
                          for t, (n, okc) in sorted(by_tenant.items())},
        "all_answered": ok == len(arm["plan"]),
    }


def _shared_parity_phase(attempts: int = 6):
    """Deterministic bit-identity pin for shared-program dispatch: two
    content-identical tenants' concurrent B=1 requests coalesce into one
    B=2 device call whose per-slot phi must equal a dedicated deployment
    dispatched at the SAME padded shape."""

    import http.client

    from distributedkernelshap_tpu.registry import ModelRegistry
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    registry = ModelRegistry()
    registry.register("lin_a", build_linear(seed=1))
    registry.register("lin_b", build_linear(seed=1))
    dedicated = build_linear(seed=1)
    shared_keys_match = (registry.resolve("lin_a").share_key
                         == registry.resolve("lin_b").share_key
                         and registry.resolve("lin_a").share_key is not None)
    server = ExplainerServer(registry=registry, host="127.0.0.1", port=0,
                             max_batch_size=2, batch_timeout_s=0.5,
                             pipeline_depth=1).start()

    def post(body, model):
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=60)
        try:
            conn.request("POST", "/explain", body=body,
                         headers={"Content-Type": "application/json",
                                  "X-DKS-Model": model})
            resp = conn.getresponse()
            return resp.status, resp.read().decode()
        finally:
            conn.close()

    def metric(name):
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=30)
        try:
            conn.request("GET", "/metrics")
            text = conn.getresponse().read().decode()
        finally:
            conn.close()
        for line in text.splitlines():
            if line.startswith(name + " "):
                return float(line.rsplit(" ", 1)[-1])
        return 0.0

    coalesced = bit_identical = False
    try:
        rng = np.random.default_rng(23)
        warm_row = rng.normal(size=(1, D)).astype(np.float32)
        post(json.dumps({"array": warm_row.tolist()}).encode(), "lin_a")
        for _ in range(attempts):
            r_a = rng.normal(size=(1, D)).astype(np.float32)
            r_b = rng.normal(size=(1, D)).astype(np.float32)
            b0 = metric("dks_serve_batches_total")
            res = [None, None]

            def fire(i, row, model):
                res[i] = post(json.dumps({"array": row.tolist()}).encode(),
                              model)

            ts = [threading.Thread(target=fire, args=(0, r_a, "lin_a"),
                                   daemon=True),
                  threading.Thread(target=fire, args=(1, r_b, "lin_b"),
                                   daemon=True)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            if any(r is None or r[0] != 200 for r in res):
                continue
            if metric("dks_serve_batches_total") - b0 != 1:
                continue  # the arrivals missed the coalesce window; retry
            coalesced = True
            ded = dedicated.explain_batch(
                np.concatenate([r_a, r_b], axis=0), split_sizes=[1, 1])
            bit_identical = (_phi_of(res[0][1]) == _phi_of(ded[0])
                             and _phi_of(res[1][1]) == _phi_of(ded[1]))
            break
    finally:
        server.stop()
    return {"share_keys_match": shared_keys_match,
            "coalesced": coalesced,
            "phi_bit_identical_vs_dedicated": bit_identical}


def run_sweep_arm(tenant_counts=(1, 2, 4, 8), n_requests=96,
                  rate_rps=200.0, passes=4):
    arms = {}
    for t in tenant_counts:
        arms[f"t{t}"] = _sweep_setup(_sweep_roster(t), n_requests, rate_rps)
    # ceiling: ONE tenant per family — the dense single-tenant-per-model
    # dispatch the cross-tenant packer should restore at 2 tenants/family.
    # When the sweep already contains that arm (t4 by default), its
    # measurement IS the ceiling — no duplicate server/warmup/passes.
    n_fam = len(SWEEP_FAMILIES)
    if n_fam not in tenant_counts:
        arms["ceiling"] = _sweep_setup(_sweep_roster(n_fam), n_requests,
                                       rate_rps)
    arms["serialized"] = _sweep_setup(_sweep_roster(max(tenant_counts)),
                                      n_requests, rate_rps, shared=False)
    # interleaved measurement rounds: every arm sees every drift regime
    for _ in range(passes):
        for arm in arms.values():
            _sweep_measure_pass(arm)
    summaries = {name: _sweep_finish(arm) for name, arm in arms.items()}
    sweep = {f"t{t}": summaries[f"t{t}"] for t in tenant_counts}
    ceiling = summaries.get("ceiling", sweep.get(f"t{n_fam}"))
    serialized = summaries["serialized"]
    parity = _shared_parity_phase()
    t_max = sweep[f"t{max(tenant_counts)}"]
    ceiling_ratio = (round(t_max["goodput_rps"] / ceiling["goodput_rps"], 3)
                     if ceiling["goodput_rps"] else None)
    serialized_ratio = (round(t_max["goodput_rps"]
                              / serialized["goodput_rps"], 3)
                        if serialized["goodput_rps"] else None)
    return {
        "sweep": sweep,
        "ceiling": ceiling,
        "serialized_baseline": serialized,
        "parity": parity,
        "passes": passes,
        "goodput_vs_ceiling_ratio": ceiling_ratio,
        "goodput_vs_serialized_ratio": serialized_ratio,
    }


def sweep_checks(sw, ceiling_frac: float) -> dict:
    t_max = sw["sweep"][max(sw["sweep"],
                            key=lambda k: int(k.lstrip("t")))]
    return {
        # every request of every arm answered — coalescing and packing
        # lose nothing
        "sweep_no_lost": all(
            arm["all_answered"]
            for arm in list(sw["sweep"].values())
            + [sw["ceiling"], sw["serialized_baseline"]]),
        # the headline: 8 mixed-path tenants within 15% of the
        # single-tenant-per-model ceiling measured in the SAME run
        "sweep_goodput_ge_ceiling_frac": (
            sw["goodput_vs_ceiling_ratio"] is not None
            and sw["goodput_vs_ceiling_ratio"] >= ceiling_frac),
        # shared programs actually engaged: 2 tenants/family dispatch at
        # (about) the ceiling's per-cycle group density, not 2x
        "sweep_shared_coalesces": (
            t_max["avg_groups_per_cycle"] is not None
            and sw["ceiling"]["avg_groups_per_cycle"] is not None
            and t_max["avg_groups_per_cycle"]
            <= sw["ceiling"]["avg_groups_per_cycle"] + 1.0),
        # the feature is not a regression vs the serialized PR-10 dispatch
        "sweep_not_worse_than_serialized": (
            sw["goodput_vs_serialized_ratio"] is not None
            and sw["goodput_vs_serialized_ratio"] >= 0.95),
        "sweep_shared_phi_bit_identical": (
            sw["parity"]["share_keys_match"]
            and sw["parity"]["coalesced"]
            and sw["parity"]["phi_bit_identical_vs_dedicated"]),
    }


# --------------------------------------------------------------------- #


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--arm", default="all",
                        choices=("core", "sweep", "all"),
                        help="core = the four PR-10 gateway arms, sweep = "
                             "the cross-tenant goodput sweep, all = both")
    parser.add_argument("--requests_per_family", type=int, default=24)
    parser.add_argument("--slo_p99_s", type=float, default=2.0,
                        help="victims' interactive p99 bound in the "
                             "noisy-tenant arm")
    parser.add_argument("--sweep_requests", type=int, default=96,
                        help="open-loop requests per sweep cycle")
    parser.add_argument("--sweep_rate_rps", type=float, default=200.0)
    parser.add_argument("--sweep_ceiling_frac", type=float, default=0.85,
                        help="minimum 8-tenant goodput as a fraction of "
                             "the single-tenant-per-model ceiling")
    parser.add_argument("--check", action="store_true",
                        help="exit 1 unless the acceptance criteria hold")
    parser.add_argument("--history", default=None,
                        help="perf-history JSONL this run appends to "
                             "(default: results/perf_history.jsonl)")
    parser.add_argument("--no-record", action="store_true",
                        help="skip the perf-history self-record")
    args = parser.parse_args()

    run_core = args.arm in ("core", "all")
    run_sweep = args.arm in ("sweep", "all")

    if run_sweep:
        sw = run_sweep_arm(n_requests=args.sweep_requests,
                           rate_rps=args.sweep_rate_rps)
    if not run_core:
        checks = sweep_checks(sw, args.sweep_ceiling_frac)
        report = {"bench": "multitenant", "arm": "sweep", "sweep": sw,
                  "checks": checks, "ok": all(checks.values())}
        if not args.no_record:
            report["perf_history"] = _record_sweep(args, sw, report["ok"])
        print(json.dumps(report))
        return 1 if (args.check and not report["ok"]) else 0

    onnx_arm = run_onnx_arm()
    multi = run_multifamily_arm(
        requests_per_family=args.requests_per_family)
    swap = run_hotswap_arm()
    noisy = run_noisy_arm(slo_p99_s=args.slo_p99_s)

    checks = {
        # ONNX logistic regression lands on the linear fast path and is
        # served end-to-end with namespace-scoped warmup + cache
        "onnx_linear_fast_path": onnx_arm["classified_path"] == "linear",
        "onnx_served_200": onnx_arm["statuses"] == [200, 200],
        "onnx_warmup_namespace_signed":
            len(onnx_arm["namespace_signed_compiles"]) > 0,
        "onnx_cache_hit_scoped": (onnx_arm["cache_hits"] >= 1
                                  and onnx_arm["dup_bit_identical"]),
        "onnx_additivity_ok": onnx_arm["additivity_ok"],
        # >=3 families concurrently, bit-identical to dedicated
        "three_families_concurrent":
            len(multi["families_served"]) >= 3,
        "paths_diverse": sorted(set(multi["paths"].values())) == [
            "exact_tn", "exact_tree", "linear"],
        "phi_bit_identical_vs_dedicated": (multi["ok"] == multi["n"]
                                           and multi["phi_mismatches"]
                                           == 0),
        # hot swap: zero lost, zero changed, post-swap answers are v2
        "hotswap_zero_lost": swap["lost"] == 0,
        "hotswap_zero_changed": swap["changed_or_mixed"] == 0,
        "hotswap_post_swap_v2": (swap["swap_completed"]
                                 and swap["swap_overlapped_traffic"]
                                 and swap["post_swap_non_v2"] == 0
                                 and swap["v2_answers"] > 0),
        "hotswap_v1_drained": swap["v1_drained_retired"],
        # noisy tenant: the flooder sheds, the victims hold their SLO
        "noisy_tenant_sheds": (noisy["noisy"]["shed_429"] > 0
                               and noisy["tenant_sheds"]["noisy"] > 0),
        "victims_never_shed": (noisy["victim_a"]["shed_429"] == 0
                               and noisy["victim_b"]["shed_429"] == 0
                               and noisy["victim_a"]["ok"]
                               == noisy["victim_a"]["n"]
                               and noisy["victim_b"]["ok"]
                               == noisy["victim_b"]["n"]),
        "victims_hold_p99_slo": (noisy["victim_interactive_p99_s"]
                                 <= args.slo_p99_s),
    }
    # core-only verdict BEFORE the sweep checks fold in: the core
    # perf-history entry must not be excluded from its baseline by a
    # failure the separate multitenant_sweep entry already records
    core_ok = all(checks.values())
    report = {
        "bench": "multitenant",
        "arm": args.arm,
        "onnx": onnx_arm,
        "multi_family": multi,
        "hot_swap": swap,
        "noisy_tenant": noisy,
        "checks": checks,
        "ok": core_ok,
    }
    if run_sweep:
        report["sweep"] = sw
        checks.update(sweep_checks(sw, args.sweep_ceiling_frac))
        report["ok"] = all(checks.values())
    if not args.no_record:
        from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

        entry = record_run(
            args.history or DEFAULT_HISTORY, bench="multitenant",
            config={"requests_per_family": args.requests_per_family,
                    "slo_p99_s": args.slo_p99_s,
                    # model identities: runs against a different roster
                    # must not share a baseline (PR 10 satellite — the
                    # gate fingerprint covers the whole config)
                    "models": [
                        {"model_id": name, "model_version": 1,
                         "family": name} for name in FAMILIES]},
            metrics={"wall_s": multi["wall_s"],
                     "victim_interactive_p99_s":
                         noisy["victim_interactive_p99_s"],
                     "goodput_rps": multi["goodput_rps"]},
            extra={"checks_ok": core_ok,
                   "paths": multi["paths"]})
        report["perf_history"] = {"git_sha": entry["git_sha"],
                                  "config_fp": entry["config_fp"]}
        if run_sweep:
            report["perf_history_sweep"] = _record_sweep(
                args, sw,
                all(sweep_checks(sw, args.sweep_ceiling_frac).values()))
    print(json.dumps(report))
    if args.check and not report["ok"]:
        return 1
    return 0


def _record_sweep(args, sw, checks_ok: bool):
    """Self-record the sweep as its OWN perf-history entry (bench
    ``multitenant_sweep``): the gated ``wall_s`` is the max-tenant arm's
    wall for a fixed request count, so a cross-tenant goodput regression
    fails ``make perf-gate`` like any other bench regression."""

    from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

    t_max_key = max(sw["sweep"], key=lambda k: int(k.lstrip("t")))
    t_max = sw["sweep"][t_max_key]
    entry = record_run(
        args.history or DEFAULT_HISTORY, bench="multitenant_sweep",
        config={"tenant_counts": sorted(int(k.lstrip("t"))
                                        for k in sw["sweep"]),
                "n_requests": args.sweep_requests,
                "rate_rps": args.sweep_rate_rps,
                "families": list(SWEEP_FAMILIES)},
        metrics={"wall_s": t_max["wall_s"],
                 "goodput_rps": t_max["goodput_rps"],
                 "ceiling_goodput_rps": sw["ceiling"]["goodput_rps"],
                 "serialized_goodput_rps":
                     sw["serialized_baseline"]["goodput_rps"]},
        extra={"checks_ok": checks_ok,
               "goodput_vs_ceiling_ratio": sw["goodput_vs_ceiling_ratio"],
               "goodput_vs_serialized_ratio":
                   sw["goodput_vs_serialized_ratio"]})
    return {"git_sha": entry["git_sha"], "config_fp": entry["config_fp"]}


if __name__ == "__main__":
    sys.exit(main())
