"""Multi-tenant gateway benchmark: one fleet, many models, hot-swappable
(standalone, CPU backend, exits nonzero on ``--check`` fail).

Four measured arms, one JSON line (ISSUE 10; ROADMAP item 4, grounded in
ONNXExplainer's format-generic Shapley framework):

1. **ONNX ingest** (run first so its compile events are fresh) — an
   ONNX-style logistic-regression graph is lifted
   (``registry/onnx_lift.py``), auto-classified onto the **linear fast
   path**, registered, and served end-to-end: its warmup-ladder rungs
   must appear in the compile accounting under ITS model namespace
   (``model=<id>@v1`` signatures) and a duplicate request must hit the
   result cache under ITS fingerprint.  Uses the real ``onnx`` package
   when installed, else the framework-free ``GraphSpec`` form of the
   same graph (reported as ``onnx_available``).
2. **Multi-family fleet** — ≥3 model families (linear softmax, lifted
   tree ensemble on the exact-TreeSHAP path, tensor-train on the exact
   contraction path) served CONCURRENTLY by one server, routed by
   ``X-DKS-Model``.  Every response must be bit-identical to a dedicated
   single-model deployment of the same predictor answering the same row.
3. **Hot swap mid-run** — version 2 of the linear tenant registers while
   an open-loop stream is in flight: zero lost answers, every answer
   bit-identical to EITHER v1 or v2 (never a mixture), and requests
   arriving after the swap completes answer v2.
4. **Noisy tenant** — a flooding tenant with a ``TenantQuota`` sheds
   (429 ``tenant_*``) while two victim tenants keep an interactive p99
   under the SLO bound and shed nothing.

Every measured run self-records into ``results/perf_history.jsonl`` with
``checks_ok`` (+ the model identities in the config fingerprint) so
``make perf-gate`` covers it.

    JAX_PLATFORMS=cpu python benchmarks/multitenant_bench.py --check
"""

import argparse
import json
import sys
import threading
import time

import numpy as np

REPO_ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO_ROOT)

from benchmarks.scheduling_bench import (  # noqa: E402
    open_loop,
    percentile,
    scrape_metrics,
)

D = 6  # feature width shared by the fleet families
ONNX_D = 9  # distinct width for the ONNX arm: its ladder must TRACE fresh


def _payload_data(payload: str):
    return json.loads(payload)["data"]


def _phi_of(payload: str):
    return json.dumps(_payload_data(payload)["shap_values"])


# --------------------------------------------------------------------- #
# model families (each builder is deterministic, so calling it twice
# yields the bit-identical "dedicated deployment" reference)
# --------------------------------------------------------------------- #


def build_linear(seed=1):
    from distributedkernelshap_tpu.models import LinearPredictor
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(seed)
    W = rng.normal(size=(D, 2)).astype(np.float32)
    b = rng.normal(size=(2,)).astype(np.float32)
    bg = np.random.default_rng(100).normal(size=(12, D)).astype(np.float32)
    return BatchKernelShapModel(LinearPredictor(W, b, activation="softmax"),
                                bg, {"link": "logit", "seed": 0}, {})


def build_tree():
    from sklearn.ensemble import HistGradientBoostingRegressor

    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(7)
    X = rng.normal(size=(200, D))
    y = X[:, 0] * 2 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    gbr = HistGradientBoostingRegressor(max_iter=10, max_depth=3,
                                        random_state=0).fit(X, y)
    bg = np.random.default_rng(101).normal(size=(12, D)).astype(np.float32)
    return BatchKernelShapModel(gbr.predict, bg, {"seed": 0}, {})


def build_tt():
    from distributedkernelshap_tpu.models.tensor_net import (
        TensorTrainPredictor,
    )
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(9)
    ranks = [1, 2, 2, 2, 2, 2, 1]
    cores = [(rng.normal(scale=0.5,
                         size=(ranks[i], ranks[i + 1])).astype(np.float32),
              rng.normal(scale=0.5,
                         size=(ranks[i], ranks[i + 1])).astype(np.float32))
             for i in range(D)]
    bg = np.random.default_rng(102).normal(size=(12, D)).astype(np.float32)
    return BatchKernelShapModel(TensorTrainPredictor(cores), bg,
                                {"seed": 0}, {})


FAMILIES = {"lin": build_linear, "tree": build_tree, "tt": build_tt}


def _serve_registry(registry, **kwargs):
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    defaults = dict(host="127.0.0.1", port=0, max_batch_size=8,
                    batch_timeout_s=0.004, pipeline_depth=2)
    defaults.update(kwargs)
    return ExplainerServer(registry=registry, **defaults).start()


def _wait_warm(server, timeout_s: float = 120.0) -> None:
    """Wait out the readiness gate so first-compile time never pollutes
    the measured request latencies (the fleet's real routers hold traffic
    on the warming 503 the same way)."""

    deadline = time.monotonic() + timeout_s
    while server.warmup_status()["state"] in ("pending", "running") \
            and time.monotonic() < deadline:
        time.sleep(0.05)


# --------------------------------------------------------------------- #
# arm 1: ONNX ingest onto the linear fast path, end-to-end
# --------------------------------------------------------------------- #


def _logreg_graph_spec(W: np.ndarray, b: np.ndarray):
    """The logistic-regression graph (Gemm -> Sigmoid), as a real ONNX
    ModelProto when the package is installed (round-tripping through
    serialized bytes, the customer hand-off shape), else as the
    equivalent GraphSpec the same translator consumes."""

    from distributedkernelshap_tpu.registry import (
        GraphSpec,
        NodeSpec,
        lift_graph,
        lift_onnx,
    )

    try:
        import onnx
        from onnx import TensorProto, helper, numpy_helper

        graph = helper.make_graph(
            [helper.make_node("Gemm", ["X", "W", "b"], ["z"]),
             helper.make_node("Sigmoid", ["z"], ["y"])],
            "logreg",
            [helper.make_tensor_value_info(
                "X", TensorProto.FLOAT, [None, W.shape[0]])],
            [helper.make_tensor_value_info(
                "y", TensorProto.FLOAT, [None, 1])],
            initializer=[numpy_helper.from_array(W, "W"),
                         numpy_helper.from_array(b, "b")])
        model = helper.make_model(graph)
        return lift_onnx(model.SerializeToString()), True
    except ImportError:
        spec = GraphSpec(
            nodes=[NodeSpec("Gemm", ("X", "W", "b"), ("z",), {}),
                   NodeSpec("Sigmoid", ("z",), ("y",), {})],
            initializers={"W": W, "b": b},
            input_name="X", output_name="y", input_dim=W.shape[0])
        return lift_graph(spec), False


def run_onnx_arm():
    from distributedkernelshap_tpu.registry import ModelRegistry
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(11)
    W = rng.normal(size=(ONNX_D, 1)).astype(np.float32)
    b = rng.normal(size=(1,)).astype(np.float32)
    pred, onnx_available = _logreg_graph_spec(W, b)

    bg = rng.normal(size=(12, ONNX_D)).astype(np.float32)
    serving = BatchKernelShapModel(pred, bg, {"link": "logit", "seed": 0},
                                  {})
    registry = ModelRegistry()
    rm = registry.register("onnx_lr", serving)
    server = _serve_registry(registry, max_batch_size=4, warmup=True,
                             cache_bytes=1 << 20)
    try:
        # the ladder must finish (and stamp its model=... compile
        # signatures) before the timed requests
        _wait_warm(server, timeout_s=60)
        row = rng.normal(size=(1, ONNX_D)).astype(np.float32)
        results = open_loop(server, [
            (0.0, row, {"X-DKS-Model": "onnx_lr"}, "first"),
            (0.1, row, {"X-DKS-Model": "onnx_lr"}, "dup"),
        ])
        metrics = scrape_metrics(server)
        statuses = sorted(s for _, s, _, _ in results)
        payloads = {tag: p for tag, s, _, p in results if s == 200}
        signed = [name for name in metrics
                  if name.startswith("dks_compile_total")
                  and "model=onnx_lr@v1" in name]
        hits = metrics.get("dks_serve_cache_hits_total", 0)
    finally:
        server.stop()
    # additivity of the served ONNX model (sanity that the lift is real)
    data = _payload_data(payloads.get("first", '{"data": {}}'))
    additive = False
    if data.get("shap_values") is not None:
        total = (np.asarray(data["shap_values"]).sum(-1)
                 + np.asarray(data["expected_value"])[:, None])
        additive = bool(np.allclose(
            total, np.asarray(data["raw"]["raw_prediction"]).T, atol=1e-3))
    return {
        "onnx_available": onnx_available,
        "classified_path": rm.path,
        "statuses": statuses,
        "warmup_state": server.warmup_status()["state"],
        "namespace_signed_compiles": signed[:4],
        "cache_hits": int(hits),
        "dup_bit_identical": (payloads.get("first") == payloads.get("dup")
                              and "first" in payloads),
        "additivity_ok": additive,
        "fingerprint": rm.fingerprint,
    }


# --------------------------------------------------------------------- #
# arm 2: >=3 families served concurrently, phi vs dedicated deployments
# --------------------------------------------------------------------- #


def run_multifamily_arm(requests_per_family=24, rate_rps=60.0, pool=6,
                        seed=0):
    from distributedkernelshap_tpu.registry import ModelRegistry

    registry = ModelRegistry()
    for name, build in FAMILIES.items():
        registry.register(name, build())
    paths = {name: registry.resolve(name).path for name in FAMILIES}

    rng = np.random.default_rng(seed)
    rows = {name: rng.normal(size=(pool, 1, D)).astype(np.float32)
            for name in FAMILIES}
    # dedicated single-model deployments: fresh, separately constructed
    # models from the same deterministic builders — the reference answers
    dedicated = {name: build() for name, build in FAMILIES.items()}
    expected = {}
    for name in FAMILIES:
        for i in range(pool):
            expected[(name, i)] = _phi_of(
                dedicated[name].explain_batch(rows[name][i])[0])

    # max_batch_size=1: the bit-identity claim is that the GATEWAY adds
    # zero numeric perturbation vs a dedicated deployment.  Coalescing
    # changes f32 reduction order at the ~1-ULP level for B>1 batches (a
    # pre-existing engine property, independent of multitenancy), so the
    # parity arm pins every device call to the dedicated deployment's
    # B=1 shape; tenants still interleave concurrently through the
    # scheduler and the pipelined dispatcher.
    server = _serve_registry(registry, max_batch_size=1, warmup=True)
    try:
        _wait_warm(server)
        plan = []
        n = requests_per_family * len(FAMILIES)
        order = [name for name in FAMILIES] * requests_per_family
        for k, name in enumerate(order):
            i = int(rng.integers(pool))
            plan.append((k / rate_rps, rows[name][i],
                         {"X-DKS-Model": name}, (name, i)))
        t0 = time.monotonic()
        results = open_loop(server, plan)
        wall = time.monotonic() - t0
        metrics = scrape_metrics(server)
    finally:
        server.stop()

    ok = [r for r in results if r[1] == 200]
    mismatches = sum(1 for tag, s, _, payload in results
                     if s == 200 and _phi_of(payload) != expected[tag])
    per_model_counts = {
        name: int(metrics.get(
            f'dks_registry_requests_total{{model="{name}"}}', 0))
        for name in FAMILIES}
    return {
        "wall_s": round(wall, 3),
        "n": n,
        "ok": len(ok),
        "goodput_rps": round(len(ok) / wall, 2),
        "paths": paths,
        "phi_mismatches": mismatches,
        "per_model_requests_total": per_model_counts,
        "families_served": sorted(set(tag[0] for tag, s, _, _ in results
                                      if s == 200)),
    }


# --------------------------------------------------------------------- #
# arm 3: hot swap mid-run
# --------------------------------------------------------------------- #


def run_hotswap_arm(n_per_segment=24, rate_rps=40.0, seed=3):
    """Two open-loop segments around a mid-run hot swap.

    Segment A streams against v1 and TRIGGERS the swap after a few
    requests, so v2's ladder warm-up, the atomic flip and v1's drain all
    overlap live traffic (in-flight requests pinned v1 and must finish
    on it).  After the swap thread joins, segment B streams again — by
    then the flip is complete, so every segment-B answer must be
    bit-identical v2.  ``max_batch_size=1`` for the same bit-identity
    reason as the multi-family arm."""

    from distributedkernelshap_tpu.registry import ModelRegistry

    registry = ModelRegistry()
    registry.register("lin", build_linear(seed=1))
    rng = np.random.default_rng(seed)
    row = rng.normal(size=(1, D)).astype(np.float32)
    v1_phi = _phi_of(build_linear(seed=1).explain_batch(row)[0])
    v2_phi = _phi_of(build_linear(seed=2).explain_batch(row)[0])

    server = _serve_registry(registry, cache_bytes=0, max_batch_size=1,
                             warmup=True)
    swap_started = threading.Event()
    swap_done = threading.Event()

    def swap():
        swap_started.wait()
        # the gateway's hot-swap: warm v2's ladder, flip, drain v1 — all
        # while segment A keeps firing
        registry.register("lin", build_linear(seed=2))
        swap_done.set()

    swapper = threading.Thread(target=swap, daemon=True)
    swapper.start()
    try:
        _wait_warm(server)
        plan_a = []
        for k in range(n_per_segment):
            plan_a.append((k / rate_rps, row, {"X-DKS-Model": "lin"}, k))

        def trigger():
            time.sleep((n_per_segment // 4) / rate_rps)
            swap_started.set()

        threading.Thread(target=trigger, daemon=True).start()
        results_a = open_loop(server, plan_a)
        swapper.join(timeout=120)
        overlapped = swap_done.is_set() and any(
            s == 200 for _, s, _, _ in results_a)
        results_b = open_loop(server, [
            (k / rate_rps, row, {"X-DKS-Model": "lin"}, k)
            for k in range(n_per_segment)])
        v1_rm = registry._models["lin"]["versions"][1]
        drained = v1_rm.state == "retired" and v1_rm.inflight == 0
    finally:
        server.stop()

    results = results_a + results_b
    lost = 2 * n_per_segment - sum(1 for _, s, _, _ in results if s == 200)
    wrong = sum(1 for _, s, _, p in results
                if s == 200 and _phi_of(p) not in (v1_phi, v2_phi))
    # every request fired after the swap completed must answer v2 (the
    # flip is atomic at admission; segment-A in-flights may be either)
    post_swap_non_v2 = sum(1 for _, s, _, p in results_b
                           if s == 200 and _phi_of(p) != v2_phi)
    v2_answers = sum(1 for _, s, _, p in results
                     if s == 200 and _phi_of(p) == v2_phi)
    return {
        "n": 2 * n_per_segment,
        "lost": lost,
        "changed_or_mixed": wrong,
        "post_swap_non_v2": post_swap_non_v2,
        "v2_answers": v2_answers,
        "swap_completed": swap_done.is_set(),
        "swap_overlapped_traffic": overlapped,
        "v1_drained_retired": drained,
    }


# --------------------------------------------------------------------- #
# arm 4: noisy tenant vs quota isolation
# --------------------------------------------------------------------- #


def run_noisy_arm(victim_requests=32, flood_requests=120,
                  victim_rate=30.0, flood_rate=150.0, flood_rows=8,
                  slo_p99_s=2.0, seed=4):
    from distributedkernelshap_tpu.registry import ModelRegistry, TenantQuota

    registry = ModelRegistry()
    registry.register("victim_a", build_linear(seed=1))
    registry.register("victim_b", build_tt())
    # the quota's in-flight bound also caps how many flood requests the
    # scheduler can COALESCE into one device batch (same tenant, same
    # engine), so a victim never waits behind an unbounded same-model
    # mega-batch — the queue-bound half of tenant isolation
    registry.register("noisy", build_linear(seed=5),
                      quota=TenantQuota(rate_per_s=5.0, burst=3,
                                        max_inflight=3))
    rng = np.random.default_rng(seed)
    server = _serve_registry(registry, max_queue_per_class=10_000,
                             warmup=True)
    try:
        # warm every tenant's ladder first: the victims' p99 must measure
        # steady-state isolation, not the TT path's first-compile
        _wait_warm(server)
        plan = []
        for k in range(victim_requests):
            for name in ("victim_a", "victim_b"):
                plan.append((k / victim_rate,
                             rng.normal(size=(1, D)).astype(np.float32),
                             {"X-DKS-Model": name,
                              "X-DKS-Priority": "interactive"},
                             name))
        for k in range(flood_requests):
            plan.append((k / flood_rate,
                         rng.normal(size=(flood_rows, D)).astype(
                             np.float32),
                         {"X-DKS-Model": "noisy",
                          "X-DKS-Priority": "interactive"},
                         "noisy"))
        results = open_loop(server, plan)
        metrics = scrape_metrics(server)
    finally:
        server.stop()

    by_tag = {}
    for tag, status, latency, _ in results:
        by_tag.setdefault(tag, []).append((status, latency))
    summary = {}
    for tag, rs in sorted(by_tag.items()):
        lat_ok = [lat for s, lat in rs if s == 200]
        summary[tag] = {
            "n": len(rs), "ok": len(lat_ok),
            "shed_429": sum(1 for s, _ in rs if s == 429),
            "p99_s": round(percentile(lat_ok, 99), 4) if lat_ok else None,
        }
    tenant_sheds = {
        name: sum(v for k, v in metrics.items()
                  if k.startswith("dks_registry_sheds_total")
                  and f'model="{name}"' in k)
        for name in ("victim_a", "victim_b", "noisy")}
    summary["victim_interactive_p99_s"] = max(
        summary["victim_a"]["p99_s"] or 0.0,
        summary["victim_b"]["p99_s"] or 0.0)
    summary["slo_p99_s"] = slo_p99_s
    summary["tenant_sheds"] = {k: int(v) for k, v in tenant_sheds.items()}
    return summary


# --------------------------------------------------------------------- #


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--requests_per_family", type=int, default=24)
    parser.add_argument("--slo_p99_s", type=float, default=2.0,
                        help="victims' interactive p99 bound in the "
                             "noisy-tenant arm")
    parser.add_argument("--check", action="store_true",
                        help="exit 1 unless the acceptance criteria hold")
    parser.add_argument("--history", default=None,
                        help="perf-history JSONL this run appends to "
                             "(default: results/perf_history.jsonl)")
    parser.add_argument("--no-record", action="store_true",
                        help="skip the perf-history self-record")
    args = parser.parse_args()

    onnx_arm = run_onnx_arm()
    multi = run_multifamily_arm(
        requests_per_family=args.requests_per_family)
    swap = run_hotswap_arm()
    noisy = run_noisy_arm(slo_p99_s=args.slo_p99_s)

    checks = {
        # ONNX logistic regression lands on the linear fast path and is
        # served end-to-end with namespace-scoped warmup + cache
        "onnx_linear_fast_path": onnx_arm["classified_path"] == "linear",
        "onnx_served_200": onnx_arm["statuses"] == [200, 200],
        "onnx_warmup_namespace_signed":
            len(onnx_arm["namespace_signed_compiles"]) > 0,
        "onnx_cache_hit_scoped": (onnx_arm["cache_hits"] >= 1
                                  and onnx_arm["dup_bit_identical"]),
        "onnx_additivity_ok": onnx_arm["additivity_ok"],
        # >=3 families concurrently, bit-identical to dedicated
        "three_families_concurrent":
            len(multi["families_served"]) >= 3,
        "paths_diverse": sorted(set(multi["paths"].values())) == [
            "exact_tn", "exact_tree", "linear"],
        "phi_bit_identical_vs_dedicated": (multi["ok"] == multi["n"]
                                           and multi["phi_mismatches"]
                                           == 0),
        # hot swap: zero lost, zero changed, post-swap answers are v2
        "hotswap_zero_lost": swap["lost"] == 0,
        "hotswap_zero_changed": swap["changed_or_mixed"] == 0,
        "hotswap_post_swap_v2": (swap["swap_completed"]
                                 and swap["swap_overlapped_traffic"]
                                 and swap["post_swap_non_v2"] == 0
                                 and swap["v2_answers"] > 0),
        "hotswap_v1_drained": swap["v1_drained_retired"],
        # noisy tenant: the flooder sheds, the victims hold their SLO
        "noisy_tenant_sheds": (noisy["noisy"]["shed_429"] > 0
                               and noisy["tenant_sheds"]["noisy"] > 0),
        "victims_never_shed": (noisy["victim_a"]["shed_429"] == 0
                               and noisy["victim_b"]["shed_429"] == 0
                               and noisy["victim_a"]["ok"]
                               == noisy["victim_a"]["n"]
                               and noisy["victim_b"]["ok"]
                               == noisy["victim_b"]["n"]),
        "victims_hold_p99_slo": (noisy["victim_interactive_p99_s"]
                                 <= args.slo_p99_s),
    }
    report = {
        "bench": "multitenant",
        "onnx": onnx_arm,
        "multi_family": multi,
        "hot_swap": swap,
        "noisy_tenant": noisy,
        "checks": checks,
        "ok": all(checks.values()),
    }
    if not args.no_record:
        from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

        entry = record_run(
            args.history or DEFAULT_HISTORY, bench="multitenant",
            config={"requests_per_family": args.requests_per_family,
                    "slo_p99_s": args.slo_p99_s,
                    # model identities: runs against a different roster
                    # must not share a baseline (PR 10 satellite — the
                    # gate fingerprint covers the whole config)
                    "models": [
                        {"model_id": name, "model_version": 1,
                         "family": name} for name in FAMILIES]},
            metrics={"wall_s": multi["wall_s"],
                     "victim_interactive_p99_s":
                         noisy["victim_interactive_p99_s"],
                     "goodput_rps": multi["goodput_rps"]},
            extra={"checks_ok": report["ok"],
                   "paths": multi["paths"]})
        report["perf_history"] = {"git_sha": entry["git_sha"],
                                  "config_fp": entry["config_fp"]}
    print(json.dumps(report))
    if args.check and not report["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
