"""One-session TPU re-validation sweep.

A wedged tunnel relay can block TPU backend init for hours; once it
recovers, the recovery discipline is to do ALL pending device work in ONE
connected process rather than reconnecting per task (each client exit is a
fresh chance to re-wedge).  This script is that one session: it runs every
measurement the round needs, in order, each step individually try/except'd
and appended as a JSON line to ``results/tpu_revalidate.jsonl`` as soon as
it finishes (a later hang cannot lose earlier numbers).

    python benchmarks/tpu_revalidate.py [--skip adult_blackbox,...]

Steps:

1. every BASELINE.json config via ``benchmarks/configs.py`` (headline adult,
   stress, lifted trees, model zoo, mnist, full covertype, host-eval
   blackbox) — post-barrier re-validation incl. the ``model_err`` external
   faithfulness columns;
2. the fused-tree-eval regression check (``tpu_regression_check.main``);
3. serving: auto-calibrated depth for coalesced (b=10) and uncoalesced
   (b=1) modes, plus fixed depths 4 and 16 for the uncoalesced mode so the
   auto-depth can be judged against hand-tuned rows;
4. single-chip pool sweep points (w=1, b 320/2560) in the reference's
   pickle convention.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join("results", "tpu_revalidate.jsonl")


def _emit(record):
    record["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    os.makedirs("results", exist_ok=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(record) + "\n")
    print(json.dumps(record), flush=True)


def _step(name, fn, on_success=None):
    t0 = time.monotonic()
    try:
        result = fn()
        _emit({"step": name, "ok": True,
               "elapsed_s": round(time.monotonic() - t0, 1),
               "result": result})
        if on_success is not None:
            try:
                on_success(result)
            except Exception as e:
                # evidence capture must never fail the sweep — but a broken
                # feed must be VISIBLE in the log, or an empty cache at
                # driver time is indistinguishable from 'no healthy window'
                _emit({"step": f"{name}:evidence_capture", "ok": False,
                       "error": f"{type(e).__name__}: {e}"})
    except Exception as e:  # keep the session going; later steps still run
        _emit({"step": name, "ok": False,
               "elapsed_s": round(time.monotonic() - t0, 1),
               "error": f"{type(e).__name__}: {e}"})


def _cache_headline(result):
    """Feed the shared on-chip evidence cache (benchmarks/_evidence.py) from
    this protocol's headline-task measurement, so a wedged driver-time
    ``bench.py`` still attaches a labelled on-chip number (VERDICT r4 #1)."""

    import jax

    from benchmarks._evidence import record_onchip_success

    if not record_onchip_success(
            dict(result, platform=jax.default_backend()),
            protocol="tpu_revalidate:config:adult"):
        # surfaces as a <step>:evidence_capture failure line in the log
        raise RuntimeError("evidence cache refused the record "
                           "(cpu platform, or no numeric value)")


#: every selectable step name (configs + the three composite steps) — the
#: single list ``--only``/``--skip`` validate against, so a future config
#: cannot silently slip into (or out of) a caller's hardcoded skip string
STEP_NAMES = ("adult", "adult_stress", "adult_trees", "adult_trees_exact",
              "mnist", "covertype", "model_zoo", "adult_blackbox",
              "regression", "serve", "pool")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--skip", default="",
                        help="comma-separated step names to skip")
    parser.add_argument("--only", default="",
                        help="comma-separated step names to run (everything "
                             "else skipped); the positive spelling callers "
                             "should prefer — a complement-of-skip string "
                             "silently re-runs any step added later")
    args = parser.parse_args()
    skip = set(filter(None, args.skip.split(",")))
    only = set(filter(None, args.only.split(",")))
    unknown = (skip | only) - set(STEP_NAMES)
    if unknown:
        parser.error(f"unknown step names {sorted(unknown)}; "
                     f"valid: {', '.join(STEP_NAMES)}")
    if only:
        skip |= set(STEP_NAMES) - only

    import jax

    t0 = time.monotonic()
    devices = jax.devices()
    _emit({"step": "backend", "ok": True,
           "elapsed_s": round(time.monotonic() - t0, 1),
           "result": {"devices": [str(d) for d in devices],
                      "backend": jax.default_backend()}})

    from benchmarks.configs import CONFIGS

    # value-per-minute order: the short configs (headline, stress, trees,
    # the exact A/B) and the two whose code changed most recently
    # (mnist/covertype) run BEFORE model_zoo — the zoo trains 8 model
    # families on one host core (~80 min observed) and must not starve the
    # rest if the relay session turns out short (round 2's window was
    # 75 min and the zoo died mid-run at the end of it)
    for name in ("adult", "adult_stress", "adult_trees", "adult_trees_exact",
                 "mnist", "covertype", "model_zoo", "adult_blackbox"):
        if name in skip:
            continue
        _step(f"config:{name}", lambda n=name: CONFIGS[n](smoke=False),
              on_success=_cache_headline if name == "adult" else None)

    if "regression" not in skip:
        from benchmarks import tpu_regression_check

        _step("regression_check",
              lambda: (tpu_regression_check.main(), "ALL CLEAR")[1])

    if "serve" not in skip:
        from distributedkernelshap_tpu.utils import load_data, load_model
        from benchmarks.serve_explanations import build_model, run_config

        data = load_data()
        predictor = load_model()
        X = data["all"]["X"]["processed"]["test"].toarray()
        model = build_model(predictor, data)
        # (replicas, max_batch_size): 0 = auto-calibrated depth
        for replicas, mbs in ((0, 10), (0, 1), (4, 1), (16, 1)):
            _step(f"serve:r{replicas}_b{mbs}",
                  lambda r=replicas, b=mbs: run_config(
                      predictor, data, X, r, b, "0.0.0.0", 0, nruns=2,
                      model=model))

    if "pool" not in skip:
        from benchmarks.pool import fit_kernel_shap_explainer, run_explainer
        from distributedkernelshap_tpu.utils import load_data, load_model

        data = load_data()
        clf = load_model()
        X = data["all"]["X"]["processed"]["test"].toarray()

        def pool_point(batch):
            opts = {"batch_size": batch, "n_devices": 1}
            ex = fit_kernel_shap_explainer(clf, data, opts)
            ex.explain(X[:batch], silent=True)  # warmup at the slab shape
            run_explainer(ex, X, opts, nruns=3)
            return f"results/ray_workers_1_bsize_{batch}_actorfr_1.0.pkl"

        def _cache_pool(pkl_path):
            # the b=2560 pool point IS the headline task (all 2560 test
            # instances, bg=100) under the reference's pool protocol — feed
            # the shared evidence cache from its pickle
            import pickle

            import jax
            import numpy as np

            from benchmarks._evidence import record_onchip_success

            with open(pkl_path, "rb") as f:
                t = float(np.median(pickle.load(f)["t_elapsed"]))
            if not record_onchip_success(
                    {"metric": "adult_2560_bg100_wall_s",
                     "value": round(t, 4), "unit": "s",
                     "platform": jax.default_backend()},
                    protocol="pool:w1_b2560"):
                raise RuntimeError("evidence cache refused the record "
                                   "(cpu platform, or no numeric value)")

        for batch in (320, 2560):
            _step(f"pool:w1_b{batch}", lambda b=batch: pool_point(b),
                  on_success=_cache_pool if batch == 2560 else None)

    _emit({"step": "done", "ok": True})


if __name__ == "__main__":
    main()
