"""Continuous-profiling / memory-ledger bench: the sampler must be
near-free and the ledger's books must balance (standalone, CPU backend,
exits nonzero on ``--check`` fail).

Five measured arms, one JSON line (ISSUE 18):

1. **Ledger exactness** — from a fresh ledger epoch, one served linear
   tenant answers a burst of requests; afterwards the ledger's total
   must EQUAL an independent walk of everything it claims to track
   (``approx_nbytes`` over the engine's device/plan-const caches plus
   the result cache's own byte counter).  The ledger cannot grade its
   own homework: the walk recomputes sizes from the live containers.
2. **Pressure drill** — with a soft budget pinned below the live total,
   further requests must fire ``memory_pressure`` (events and evicted
   bytes both nonzero) and the drill's canary request must come back
   BIT-IDENTICAL after eviction — pressure may only ever force a
   re-upload/recompute, never change an answer.
3. **Sampler overhead** — one live server, the sampler paused/resumed
   PER REQUEST (strict on/off alternation, the drift-robust
   methodology the cost-attribution bench settled on): the sampled
   pool's median request latency must sit within 1% of the unsampled
   pool's.  The ratio self-records as ``prof_overhead_factor`` so
   ``make perf-gate`` covers sampler-overhead regressions.
4. **Hot-path attribution** — a dedicated ``hot``-role thread runs
   ``explain_batch`` in a tight loop under a private high-rate sampler;
   at least half of that role's samples must carry an engine
   (``kernel_shap``) frame, i.e. the profiler attributes hot time to
   the code actually burning it, not to scaffolding.
5. **Federation** — two in-process replicas behind a ``FanInProxy``;
   with the sampler frozen, the proxy's ``/profilez?federate=1`` merge
   must equal the fold of the per-replica collapsed pages.

Self-records into ``results/perf_history.jsonl`` with ``checks_ok``.

    JAX_PLATFORMS=cpu python benchmarks/profile_bench.py --check
"""

import argparse
import gc
import json
import statistics
import sys
import threading
import time

import numpy as np

REPO_ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO_ROOT)

from benchmarks.cost_attribution_bench import (  # noqa: E402
    http_get,
    post_explain,
    serve_fleet,
)
from benchmarks.multitenant_bench import build_linear  # noqa: E402

D = 6  # the multitenant builders' feature width


# --------------------------------------------------------------------- #
# arms 1+2: ledger exactness, then the pressure drill on the same fleet
# --------------------------------------------------------------------- #


def independent_walk_bytes(model, server) -> int:
    """Recompute, from the live containers, every byte the ledger claims
    to be tracking for this server: ``approx_nbytes`` over the engine's
    device/plan-const cache VALUES plus the result cache's own byte
    counter.  Sizes are recomputed here, not read back from the ledger,
    so agreement is a real cross-check."""

    from distributedkernelshap_tpu.observability.memledger import (
        approx_nbytes,
    )

    engine = model.explainer._explainer
    total = 0
    for cache in (engine._dev_cache, engine._plan_consts_cache):
        for value in list(cache.values()):
            total += approx_nbytes(value)
    if server._cache is not None:
        total += server._cache.stats()["bytes"]
    return total


def run_ledger_arm(requests=12, seed=3):
    """Fresh ledger epoch -> serve a burst -> books must balance."""

    from distributedkernelshap_tpu.observability.memledger import memledger

    gc.collect()  # dead caches from earlier epochs release their charges
    led = memledger()
    led.reset()
    model = build_linear(seed=seed)
    server, _registry = serve_fleet([("tenant-led", model)],
                                    cache_bytes=1 << 20)
    rng = np.random.default_rng(42)
    statuses = []
    for _ in range(requests):
        row = rng.normal(size=(1, D)).astype(np.float32)
        status, _ = post_explain(server.host, server.port, row,
                                 model="tenant-led")
        statuses.append(status)
    ledger_total = led.total_bytes()
    walk_total = independent_walk_bytes(model, server)
    result = {
        "requests": requests,
        "all_ok": all(s == 200 for s in statuses),
        "ledger_total_bytes": ledger_total,
        "independent_walk_bytes": walk_total,
        "exact": ledger_total == walk_total,
        "owners": led.owner_totals(),
        "high_water_bytes": led.high_water_bytes(),
    }
    # the pressure drill reuses this live fleet, then tears it down
    return result, (server, model, led)


def run_pressure_arm(fleet, extra_requests=8):
    """Pin the budget below the live total, push more work through, and
    demand (a) pressure fired, (b) bytes were actually evicted, (c) the
    canary answer survives eviction bit-for-bit."""

    server, model, led = fleet
    rng = np.random.default_rng(7)
    canary = rng.normal(size=(1, D)).astype(np.float32)
    try:
        status, baseline = post_explain(server.host, server.port, canary,
                                        model="tenant-led")
        events_before = led.pressure_events()
        evicted_before = led.evicted_bytes()
        led.set_budget(max(4096, led.total_bytes() // 2))
        try:
            statuses = []
            for _ in range(extra_requests):
                row = rng.normal(size=(1, D)).astype(np.float32)
                s, _ = post_explain(server.host, server.port, row,
                                    model="tenant-led")
                statuses.append(s)
            led.poke()
            events = led.pressure_events() - events_before
            evicted = led.evicted_bytes() - evicted_before
            status2, after = post_explain(server.host, server.port,
                                          canary, model="tenant-led")
        finally:
            led.set_budget(0)
    finally:
        server.stop()
    return {
        "all_ok": (status == 200 and status2 == 200
                   and all(s == 200 for s in statuses)),
        "pressure_events": events,
        "evicted_bytes": evicted,
        "answer_bit_identical": after == baseline,
        "total_after_drill_bytes": led.total_bytes(),
    }


# --------------------------------------------------------------------- #
# arm 3: sampler overhead (the gated sentinel)
# --------------------------------------------------------------------- #


def run_overhead_arm(requests=300, seed=13):
    """Sampler overhead on ONE live server, pausing/resuming the
    process sampler PER REQUEST (strict alternation — any drift profile
    hits both pools identically; the only difference between the pooled
    medians is the sweep the sampler runs while a request is in
    flight).  The on/off median ratio records as
    ``prof_overhead_factor`` — pinned near 1.0 by construction, so the
    perf gate's relative threshold reads directly as overhead drift."""

    from distributedkernelshap_tpu.observability.contprof import contprof

    model = build_linear(seed=1)
    server, _registry = serve_fleet([("tenant-ovh", model)])
    prof = contprof()
    # hold the auto-disable valve open for the arm: if the safety valve
    # fired mid-measurement the "on" pool would silently sample nothing
    # and the ratio would be meaningless — the bench wants the true cost
    budget_before = prof.overhead_budget
    prof.overhead_budget = 10.0
    lat = {"on": [], "off": []}
    rng = np.random.default_rng(seed)
    try:
        for _ in range(10):  # untimed warm pass
            post_explain(server.host, server.port,
                         rng.normal(size=(1, D)).astype(np.float32),
                         model="tenant-ovh")
        for i in range(2 * requests):
            arm = "on" if i % 2 == 0 else "off"
            if arm == "on":
                prof.resume()
            else:
                prof.pause()
            row = rng.normal(size=(1, D)).astype(np.float32)
            t0 = time.monotonic()
            status, _ = post_explain(server.host, server.port, row,
                                     model="tenant-ovh")
            assert status == 200
            lat[arm].append(time.monotonic() - t0)
        sampler_alive = prof.running and not prof.auto_disabled
    finally:
        prof.resume()
        prof.overhead_budget = budget_before
        server.stop()
    med_on = statistics.median(lat["on"])
    med_off = statistics.median(lat["off"])
    return {"median_on_s": round(med_on, 6),
            "median_off_s": round(med_off, 6),
            "overhead_frac": round(med_on / med_off - 1.0, 4),
            "prof_overhead_factor": round(med_on / med_off, 4),
            "sampler_alive": sampler_alive,
            "requests_per_arm": requests}


# --------------------------------------------------------------------- #
# arm 4: hot-path attribution
# --------------------------------------------------------------------- #


def run_hotpath_arm(duration_s=1.5, hz=97.0):
    """A ``hot``-role thread burns real engine time in a loop under a
    private high-rate sampler; the profile must pin the majority of
    that role's samples on frames from the engine module — the whole
    point of a profiler is that hot time lands on the code burning it."""

    from distributedkernelshap_tpu.observability.contprof import (
        ContProf,
        parse_collapsed,
    )

    model = build_linear(seed=9)
    rng = np.random.default_rng(2)
    X = rng.normal(size=(4, D)).astype(np.float32)
    model.explain_batch(X, split_sizes=[4])  # compile outside the profile
    prof = ContProf(hz=hz)
    stop = threading.Event()

    def hot_loop():
        prof.register_current_thread("hot")
        while not stop.is_set():
            model.explain_batch(X, split_sizes=[4])

    worker = threading.Thread(target=hot_loop, daemon=True)
    prof.start()
    worker.start()
    try:
        time.sleep(duration_s)
    finally:
        stop.set()
        worker.join(30)
        prof.stop()
    counts = parse_collapsed(prof.collapsed())
    hot_total = sum(n for s, n in counts.items()
                    if s.startswith("thread:hot"))
    hot_engine = sum(n for s, n in counts.items()
                     if s.startswith("thread:hot") and "kernel_shap:" in s)
    return {"hot_samples": hot_total,
            "engine_samples": hot_engine,
            "engine_frac": round(hot_engine / hot_total, 4)
            if hot_total else 0.0,
            "auto_disabled": prof.stats()["auto_disabled"]}


# --------------------------------------------------------------------- #
# arm 5: federated /profilez
# --------------------------------------------------------------------- #


def run_federation_arm():
    """Two replicas behind a proxy: with the sampler frozen so the scrape
    is a fixed point, the proxy's federated merge must equal the fold of
    the per-replica collapsed pages."""

    from distributedkernelshap_tpu.observability.contprof import (
        contprof,
        merge_collapsed,
        parse_collapsed,
    )
    from distributedkernelshap_tpu.serving.replicas import FanInProxy

    s1, _r1 = serve_fleet([("tenant-fed", build_linear(seed=11))])
    s2, _r2 = serve_fleet([("tenant-fed", build_linear(seed=12))])
    proxy = FanInProxy([(s1.host, s1.port), (s2.host, s2.port)],
                       probe_interval_s=3600).start()
    prof = contprof()
    try:
        deadline = time.monotonic() + 10.0
        while prof.samples_total() == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        prof.pause()
        try:
            fed = http_get(proxy.host, proxy.port, "/profilez?federate=1")
            solos = [http_get(s.host, s.port, "/profilez?format=collapsed")
                     for s in (s1, s2)]
        finally:
            prof.resume()
    finally:
        proxy.stop()
        s1.stop()
        s2.stop()
    fed_counts = parse_collapsed(fed)
    merged = parse_collapsed(merge_collapsed(solos))
    return {"federated_samples": sum(fed_counts.values()),
            "matches_replica_fold": fed_counts == merged}


# --------------------------------------------------------------------- #
# checks / record / main
# --------------------------------------------------------------------- #


def run_checks(result):
    led = result["ledger"]
    prs = result["pressure"]
    ovh = result["overhead"]
    hot = result["hotpath"]
    fed = result["federation"]
    return {
        "ledger_books_balance": led["all_ok"] and led["exact"],
        "ledger_tracks_nonzero": led["independent_walk_bytes"] > 0,
        "pressure_fired_and_evicted": (
            prs["all_ok"] and prs["pressure_events"] > 0
            and prs["evicted_bytes"] > 0),
        "eviction_answer_bit_identical": prs["answer_bit_identical"],
        "sampler_overhead_le_1pct": (
            ovh["sampler_alive"] and ovh["overhead_frac"] <= 0.01),
        "hot_engine_frames_dominate": (
            hot["hot_samples"] > 0 and hot["engine_frac"] >= 0.5
            and not hot["auto_disabled"]),
        "federated_matches_replica_fold": (
            fed["federated_samples"] > 0 and fed["matches_replica_fold"]),
    }


def record(result, checks_ok, no_record=False):
    if no_record:
        return
    from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

    record_run(
        DEFAULT_HISTORY, "profile",
        config={"overhead_requests":
                result["config"]["overhead_requests"],
                "ledger_requests": result["config"]["ledger_requests"],
                "hot_duration_s": result["config"]["hot_duration_s"]},
        metrics={"wall_s": result["wall_s"],
                 # the sampler-overhead sentinel perf-gate watches: the
                 # on/off median latency ratio (a sampler that got
                 # expensive moves it off 1.0)
                 "prof_overhead_factor":
                     result["overhead"]["prof_overhead_factor"]},
        extra={"checks_ok": checks_ok,
               "overhead_frac": result["overhead"]["overhead_frac"],
               "engine_frac": result["hotpath"]["engine_frac"],
               "ledger_total_bytes":
                   result["ledger"]["ledger_total_bytes"]})


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--check", action="store_true",
                        help="exit nonzero unless every criterion holds")
    parser.add_argument("--ledger-requests", type=int, default=12)
    parser.add_argument("--overhead-requests", type=int, default=300,
                        help="requests per overhead arm (per-request "
                             "pause/resume alternation on one server)")
    parser.add_argument("--hot-duration", type=float, default=1.5,
                        help="seconds the hot-path arm burns under the "
                             "private high-rate sampler")
    parser.add_argument("--no-record", action="store_true",
                        help="skip the perf-history self-record")
    args = parser.parse_args()

    t0 = time.monotonic()
    result = {"config": {"ledger_requests": args.ledger_requests,
                         "overhead_requests": args.overhead_requests,
                         "hot_duration_s": args.hot_duration}}
    result["ledger"], fleet = run_ledger_arm(
        requests=args.ledger_requests)
    result["pressure"] = run_pressure_arm(fleet)
    result["overhead"] = run_overhead_arm(
        requests=args.overhead_requests)
    result["hotpath"] = run_hotpath_arm(duration_s=args.hot_duration)
    result["federation"] = run_federation_arm()
    result["wall_s"] = round(time.monotonic() - t0, 2)
    checks = run_checks(result)
    result["checks"] = checks
    checks_ok = all(checks.values())
    result["checks_ok"] = checks_ok
    record(result, checks_ok, no_record=args.no_record)
    print(json.dumps(result))
    if args.check and not checks_ok:
        failed = [k for k, v in checks.items() if not v]
        print(f"profile_bench: FAILED {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
