"""Shared benchmark CLI helpers."""


def add_platform_flag(parser) -> None:
    parser.add_argument(
        "--platform", default=None, type=str,
        help="Override the JAX platform (e.g. 'cpu'). NB: in environments "
             "where jax is pre-imported at interpreter start, the "
             "JAX_PLATFORMS env var is not a reliable override; this flag "
             "uses jax.config.update before any backend is initialised.")


def apply_platform(args) -> None:
    if getattr(args, "platform", None):
        import jax

        jax.config.update("jax_platforms", args.platform)
