"""Shared benchmark CLI helpers."""


def add_platform_flag(parser) -> None:
    parser.add_argument(
        "--platform", default=None, type=str,
        help="Override the JAX platform (e.g. 'cpu'). NB: in environments "
             "where jax is pre-imported at interpreter start, the "
             "JAX_PLATFORMS env var is not a reliable override; this flag "
             "uses jax.config.update before any backend is initialised.")
    parser.add_argument(
        "--cpu_devices", default=None, type=int,
        help="With --platform cpu: number of virtual CPU devices (so "
             "--workers N actually gets an N-device mesh, mirroring the "
             "XLA_FLAGS=--xla_force_host_platform_device_count recipe).")


def apply_platform(args) -> None:
    if getattr(args, "platform", None):
        import jax

        jax.config.update("jax_platforms", args.platform)
        if getattr(args, "cpu_devices", None):
            if args.platform == "cpu":
                from distributedkernelshap_tpu.compat import \
                    force_cpu_devices

                force_cpu_devices(args.cpu_devices)
            else:
                import logging

                logging.getLogger(__name__).warning(
                    "--cpu_devices only applies with --platform cpu; ignoring")
    elif getattr(args, "cpu_devices", None):
        import logging

        logging.getLogger(__name__).warning(
            "--cpu_devices has no effect without --platform cpu; ignoring")
