"""TPU regression check for the fused tree-eval miscompilation.

Reproduces the exact failing configuration of 2026-07-30 (B=8 instances,
nsamples=64, the 100-row Adult background, HistGradientBoosting max_iter=50)
and asserts the three invariants the bug violated:

1. the masked fast path equals the row-materialising generic path;
2. the device predictor equals sklearn on the synthetic rows;
3. full-engine phi satisfies additivity against the ORIGINAL sklearn model
   (not just the engine's internal raw predictions, which hold by WLS
   construction regardless).

Run on a real TPU after any change to the tree evaluation, XLA version, or
jax upgrade:  ``python benchmarks/tpu_regression_check.py``.  All-clear
prints one OK line per invariant; any violation raises.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from sklearn.ensemble import HistGradientBoostingClassifier

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.models import TreeEnsemblePredictor, as_predictor
    from distributedkernelshap_tpu.ops.coalitions import coalition_plan
    from distributedkernelshap_tpu.ops.explain import _ey_generic, groups_to_matrix
    from distributedkernelshap_tpu.utils import load_data

    data = load_data()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    Xtr = data["all"]["X"]["processed"]["train"].toarray()
    ytr = data["all"]["y"]["train"]
    clf = HistGradientBoostingClassifier(max_iter=50, random_state=0).fit(Xtr, ytr)
    pred = as_predictor(clf.predict_proba, example_dim=Xtr.shape[1])
    assert isinstance(pred, TreeEnsemblePredictor)

    Xall = data["all"]["X"]["processed"]["test"].toarray().astype(np.float32)
    bgd = data["background"]["X"]["preprocessed"]
    bg = np.asarray(bgd.todense() if hasattr(bgd, "todense") else bgd,
                    dtype=np.float32)
    G = groups_to_matrix(g, Xall.shape[1])
    plan = coalition_plan(G.shape[0], nsamples=64, seed=0)
    mask = np.asarray(plan.mask, np.float32)
    bgw = np.full(bg.shape[0], 1.0 / bg.shape[0], np.float32)

    for B in (4, 8, 16, 256):
        X = Xall[:B]
        ey_rows = np.asarray(_ey_generic(pred, X, bg, bgw, mask @ G, chunk=8))
        ey_fast = np.asarray(pred.masked_ey(X, bg, bgw, mask, G))
        err = np.abs(ey_fast - ey_rows).max()
        assert err < 1e-4, f"masked vs generic diverge at B={B}: {err}"
        print(f"OK masked==generic at B={B} (err {err:.2e})")

    # full engine against the original model
    ex = KernelShap(clf.predict_proba, link="logit", feature_names=gn, seed=0)
    ex.fit(data["background"]["X"]["preprocessed"], group_names=gn, groups=g)
    for B in (256, 2560):
        X = Xall[:B]
        res = ex.explain(X, silent=True)
        proba = np.clip(clf.predict_proba(X.astype(np.float64)), 1e-7, 1 - 1e-7)
        err = max(abs(res.shap_values[k].sum(1) + res.expected_value[k]
                      - np.log(proba[:, k] / (1 - proba[:, k]))).max()
                  for k in range(2))
        assert err < 1e-2, f"engine phi vs sklearn diverge at B={B}: {err}"
        print(f"OK engine additivity vs sklearn at B={B} (err {err:.2e})")
    print("ALL CLEAR")


if __name__ == "__main__":
    main()
