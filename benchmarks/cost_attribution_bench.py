"""Tenant cost-attribution bench: the meter's books must balance
(standalone, CPU backend, exits nonzero on ``--check`` fail).

Four measured arms, one JSON line (ISSUE 13):

1. **Attribution** — a 4-tenant mixed-path fleet (two content-identical
   linear tenants — the shared-program pair — an exact-TN tensor-train
   tenant and a sampled callable tenant) serves an open-loop burst
   stream twice: once with cross-tenant shared batching ON, once
   serialized (``shared_batching=False``).  In BOTH modes the sum of
   ``dks_device_seconds_total`` over every ``(model, version, path)``
   must land within 5% of the **directly measured** dispatch total — an
   independent per-call dispatch→fetch clock wrapped around each
   model's ``explain_batch(_async)`` by the bench itself (compile delta
   subtracted on both sides), so the meter cannot grade its own
   homework.
2. **Metering overhead** — one live server, the meter toggled PER
   REQUEST (strict on/off alternation, so drift hits both pools
   identically — the drift-robust refinement of the PR-4
   sampler-overhead methodology): the metered pool's median request
   latency must sit within 1% of the unmetered pool's.  The ON median
   self-records as ``metered_median_s`` so ``make perf-gate`` covers
   metering-overhead regressions.
3. **Fleet rollup** — two in-process replicas behind a ``FanInProxy``
   serve the tenants; after the stream quiesces, ``/fleetz`` per-tenant
   device-seconds must EQUAL the sum of the per-replica ``/metrics``
   scrapes (and ``/metrics?federate=1`` must re-validate under
   ``validate_exposition``).
4. **Exemplar round trip** — a deliberately-breaching per-tenant
   latency SLO (5 ms threshold, seconds-scale windows) must fire on
   ``/statusz``, and a trace exemplar pulled from ``/debugz`` for the
   breaching tenant must resolve to followable spans that survive the
   Perfetto ``trace_event`` conversion round trip.

Self-records into ``results/perf_history.jsonl`` with ``checks_ok``.

    JAX_PLATFORMS=cpu python benchmarks/cost_attribution_bench.py --check
"""

import argparse
import json
import statistics
import sys
import threading
import time

import numpy as np

REPO_ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO_ROOT)

from benchmarks.multitenant_bench import (  # noqa: E402
    build_linear,
    build_sampled,
    build_tt,
    _wait_warm,
)

D = 6  # the multitenant builders' feature width


# --------------------------------------------------------------------- #
# direct dispatch-time instrumentation (the meter's independent check)
# --------------------------------------------------------------------- #


class DispatchClock:
    """Independent dispatch→fetch wall accounting, shared by every
    instrumented model.  The serving meter measures the same boundary
    from the server side; this clock measures it from the model side,
    so agreement is a real cross-check, not a tautology."""

    def __init__(self):
        self.measuring = False
        self.total = 0.0
        self.calls = 0
        self._lock = threading.Lock()

    def add(self, seconds: float) -> None:
        with self._lock:
            if self.measuring:
                self.total += seconds
                self.calls += 1

    def reset(self) -> None:
        with self._lock:
            self.total = 0.0
            self.calls = 0


def instrument(model, clock: DispatchClock):
    """Shadow ``explain_batch(_async)`` with timing closures on the
    INSTANCE (the class, its engine and the share-eligibility probes are
    untouched).  Idempotent per model."""

    if getattr(model, "_dks_bench_clock", None) is clock:
        return model
    orig_async = model.explain_batch_async
    orig_sync = model.explain_batch

    def timed_async(instances, **kw):
        t0 = time.monotonic()
        fin = orig_async(instances, **kw)

        def timed_fin():
            try:
                return fin()
            finally:
                clock.add(time.monotonic() - t0)

        return timed_fin

    def timed_sync(instances, **kw):
        t0 = time.monotonic()
        try:
            return orig_sync(instances, **kw)
        finally:
            clock.add(time.monotonic() - t0)

    model.explain_batch_async = timed_async
    model.explain_batch = timed_sync
    model._dks_bench_clock = clock
    return model


# --------------------------------------------------------------------- #
# fleet plumbing
# --------------------------------------------------------------------- #


ROSTER = (("lin0", lambda: build_linear(seed=1)),
          ("lin1", lambda: build_linear(seed=1)),  # content-identical pair
          ("tt0", build_tt),
          ("samp0", build_sampled))

_MODELS = {}


def roster_models(clock):
    """Build (once) and instrument the 4-tenant roster; reused across
    arms so each engine compiles its ladder once."""

    for name, builder in ROSTER:
        if name not in _MODELS:
            _MODELS[name] = instrument(builder(), clock)
    return [(name, _MODELS[name]) for name, _ in ROSTER]


def serve_fleet(models, shared=True, **kwargs):
    from distributedkernelshap_tpu.registry import ModelRegistry
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    registry = ModelRegistry()
    for name, model in models:
        registry.register(name, model)
    defaults = dict(host="127.0.0.1", port=0, max_batch_size=8,
                    batch_timeout_s=0.004, pipeline_depth=2,
                    shared_batching=shared, warmup=True)
    defaults.update(kwargs)
    server = ExplainerServer(registry=registry, **defaults).start()
    _wait_warm(server)
    return server, registry


def post_explain(host, port, row, model=None, timeout=60):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        headers = {"Content-Type": "application/json"}
        if model is not None:
            headers["X-DKS-Model"] = model
        conn.request("POST", "/explain",
                     body=json.dumps({"array": row.tolist()}).encode(),
                     headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def http_get(host, port, path, timeout=60):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        return conn.getresponse().read().decode()
    finally:
        conn.close()


def burst_stream(server, tenants, bursts, rng, record=None):
    """``bursts`` rounds of one-concurrent-request-per-tenant (the
    coalescing shape shared batching exists for); every answer must be
    200.  ``record`` collects (tenant, latency_s)."""

    errors = []

    def fire(tenant, row):
        t0 = time.monotonic()
        status, payload = post_explain(server.host, server.port, row,
                                       model=tenant)
        if status != 200:
            errors.append((tenant, status, payload[:120]))
        elif record is not None:
            record.append((tenant, time.monotonic() - t0))

    for _ in range(bursts):
        threads = [threading.Thread(
            target=fire, args=(tenant,
                               rng.normal(size=(1, D)).astype(np.float32)))
            for tenant, _ in tenants]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    return errors


def metered_device_seconds(server):
    """Sum (and per-tenant split of) dks_device_seconds_total."""

    metric = server.metrics.get("dks_device_seconds_total")
    series = metric.collect()["series"]
    per_tenant = {}
    for (model, version, path), value in series.items():
        per_tenant[model] = per_tenant.get(model, 0.0) + value
    return sum(per_tenant.values()), per_tenant


# --------------------------------------------------------------------- #
# arm 1: attribution (shared + serialized)
# --------------------------------------------------------------------- #


def run_attribution_arm(clock, bursts=24, seed=11):
    from distributedkernelshap_tpu.runtime.compile_cache import (
        compile_events,
    )

    out = {}
    for mode, shared in (("shared", True), ("serialized", False)):
        models = roster_models(clock)
        server, registry = serve_fleet(models, shared=shared)
        rng = np.random.default_rng(seed)
        try:
            # one untimed pass settles any residual first-shape work
            errors = burst_stream(server, models, 2, rng)
            assert not errors, errors
            base_total, _ = metered_device_seconds(server)
            compile0 = compile_events().total_seconds()
            clock.reset()
            clock.measuring = True
            errors = burst_stream(server, models, bursts, rng)
            clock.measuring = False
            assert not errors, errors
            compile_delta = compile_events().total_seconds() - compile0
            direct = max(1e-9, clock.total - compile_delta)
            total, per_tenant = metered_device_seconds(server)
            total -= base_total
            gap = abs(total - direct) / direct
            groups = server.metrics.get("dks_serve_batch_groups").value()
            out[mode] = {
                "direct_dispatch_s": round(direct, 4),
                "metered_total_s": round(total, 4),
                "attribution_gap": round(gap, 4),
                "compile_excluded_s": round(compile_delta, 4),
                "per_tenant_s": {k: round(v, 4)
                                 for k, v in sorted(per_tenant.items())},
                "dispatch_calls": clock.calls,
                "batch_group_cycles": groups["count"],
                "all_tenants_attributed": all(
                    per_tenant.get(name, 0.0) > 0 for name, _ in models),
            }
        finally:
            server.stop()
    return out


# --------------------------------------------------------------------- #
# arm 2: metering overhead (off/on/on/off)
# --------------------------------------------------------------------- #


def run_overhead_arm(clock, requests=400, seed=13):
    """Meter overhead on ONE live server, toggling the meter's enabled
    flag PER REQUEST (strict on/off alternation).  One server means one
    engine, one process state, one HTTP stack, and per-request
    alternation means any drift profile hits both pools identically —
    the only difference between the pooled medians is the meter's
    write path, which is exactly what the ≤1% criterion is about.
    (Separate servers per arm measured 10%+ "overhead" that was
    entirely spin-up drift; pass-granular toggling still aliased
    multi-second drift waves into a 2 ms phantom.)  ``requests`` is the
    per-arm count; at ~11 ms per request the median's standard error is
    ≈0.4% of it, comfortably inside the 1% bound for a meter whose
    measured compute cost is ~40 µs."""

    lin = _MODELS.get("lin0") or instrument(build_linear(seed=1), clock)
    _MODELS.setdefault("lin0", lin)
    server, registry = serve_fleet([("lin0", lin)], shared=True)
    lat = {"on": [], "off": []}
    rng = np.random.default_rng(seed)
    try:
        for _ in range(10):  # untimed warm pass
            post_explain(server.host, server.port,
                         rng.normal(size=(1, D)).astype(np.float32),
                         model="lin0")
        for i in range(2 * requests):
            arm = "on" if i % 2 == 0 else "off"
            server._costmeter.enabled = (arm == "on")
            row = rng.normal(size=(1, D)).astype(np.float32)
            t0 = time.monotonic()
            status, _ = post_explain(server.host, server.port, row,
                                     model="lin0")
            assert status == 200
            lat[arm].append(time.monotonic() - t0)
    finally:
        server._costmeter.enabled = True
        server.stop()
    med_on = statistics.median(lat["on"])
    med_off = statistics.median(lat["off"])
    return {"median_on_s": round(med_on, 6),
            "median_off_s": round(med_off, 6),
            "overhead_frac": round(med_on / med_off - 1.0, 4),
            "requests_per_arm": requests}


# --------------------------------------------------------------------- #
# arm 3: federated fleet rollup
# --------------------------------------------------------------------- #


def run_fleet_arm(clock, bursts=10, seed=17):
    from distributedkernelshap_tpu.observability.metrics import (
        parse_exposition,
        validate_exposition,
    )
    from distributedkernelshap_tpu.serving.replicas import FanInProxy

    models = roster_models(clock)[:2]  # lin pair is plenty for the sums
    replicas, proxy = [], None
    try:
        replicas = [serve_fleet(models, shared=True) for _ in range(2)]
        proxy = FanInProxy([("127.0.0.1", srv.port)
                            for srv, _ in replicas]).start()
        rng = np.random.default_rng(seed)
        errors = []
        for i in range(bursts):
            for tenant, _ in models:
                status, payload = post_explain(
                    "127.0.0.1", proxy.port,
                    rng.normal(size=(1, D)).astype(np.float32),
                    model=tenant)
                if status != 200:
                    errors.append((tenant, status, payload[:120]))
        assert not errors, errors
        # quiesced: counters static, so the two scrape passes see the
        # same values and equality is exact up to the rollup's rounding
        direct = {}
        for srv, _ in replicas:
            page = parse_exposition(http_get(srv.host, srv.port,
                                             "/metrics"))
            for _, labels, value in \
                    page["dks_device_seconds_total"]["samples"]:
                direct[labels["model"]] = \
                    direct.get(labels["model"], 0.0) + value
        fleetz = json.loads(http_get("127.0.0.1", proxy.port, "/fleetz"))
        fed_page = http_get("127.0.0.1", proxy.port, "/metrics?federate=1")
        fed_problems = validate_exposition(fed_page)
        rollup_gap = max(
            abs(fleetz["tenants"].get(m, {}).get("device_seconds", 0.0)
                - v) for m, v in direct.items())
        return {
            "per_tenant_direct_s": {k: round(v, 4)
                                    for k, v in sorted(direct.items())},
            "per_tenant_fleetz_s": {
                m: round(t.get("device_seconds", 0.0), 4)
                for m, t in sorted(fleetz["tenants"].items())},
            "rollup_matches_direct_sum": rollup_gap < 1e-5,
            "federated_page_valid": fed_problems == [],
            "federated_problems": fed_problems[:5],
            "replicas_scraped": int(
                proxy.metrics.get("dks_fleet_replicas_scraped").value()),
        }
    finally:
        if proxy is not None:
            proxy.stop()
        for srv, _ in replicas:
            srv.stop()


# --------------------------------------------------------------------- #
# arm 4: SLO-breach exemplar → Perfetto round trip
# --------------------------------------------------------------------- #


def run_exemplar_arm(clock, requests=16, seed=19):
    import distributedkernelshap_tpu.observability.tracing as tracing
    from distributedkernelshap_tpu.observability.slo import (
        BurnRateWindow,
        default_server_slos,
        tenant_slos,
    )

    tracer = tracing.tracer()
    was_enabled = tracer.enabled
    tracer.enable()
    # seconds-scale windows + a 5 ms threshold: real request latencies
    # (tens of ms on this engine) breach within a couple of health ticks
    fast = (BurnRateWindow(long_s=6.0, short_s=2.0, factor=1.0),)
    slos = default_server_slos(windows=fast) + tenant_slos(
        ["lin0"], windows=fast, latency_target=(0.005, 0.90))
    models = roster_models(clock)[:1]
    server, registry = serve_fleet(models, shared=True, slos=slos,
                                   health_interval_s=0.2)
    rng = np.random.default_rng(seed)
    try:
        # traffic keeps flowing WHILE the poller watches: the breach
        # condition needs burn >= factor in the SHORT window too, so the
        # stream must still be violating when /statusz evaluates it (a
        # fire-then-poll shape can watch the short window drain empty
        # before the first poll)
        stop_traffic = threading.Event()
        sent = [0]

        def traffic():
            while not stop_traffic.is_set():
                status, _ = post_explain(server.host, server.port,
                                         rng.normal(size=(1, D)).astype(
                                             np.float32), model="lin0")
                if status == 200:
                    sent[0] += 1
                time.sleep(0.05)

        feeder = threading.Thread(target=traffic, daemon=True)
        feeder.start()
        breached = False
        deadline = time.monotonic() + 20.0
        try:
            while time.monotonic() < deadline and not breached:
                doc = json.loads(http_get(server.host, server.port,
                                          "/statusz?format=json"))
                breached = any(s["name"] == "tenant:lin0_latency"
                               and s["breached"] for s in doc["slos"])
                if not breached:
                    time.sleep(0.3)
        finally:
            stop_traffic.set()
            feeder.join(timeout=5.0)
        assert sent[0] >= requests // 2, f"only {sent[0]} answered"
        dbg = json.loads(http_get(server.host, server.port, "/debugz"))
        breach_ex = [e for e in dbg["exemplars"]
                     if e["metric"] == "dks_tenant_latency_seconds"
                     and e["labels"].get("model") == "lin0"
                     and e["value"] > 0.005]
        followable = round_trips = False
        if breach_ex:
            trace_id = breach_ex[0]["trace_id"]
            spans = [s for s in tracer.spans() if s.trace_id == trace_id]
            followable = any(s.name == "server.request" for s in spans)
            restored = tracing.from_chrome_trace(tracing.chrome_trace(spans))
            round_trips = (
                len(restored) == len(spans)
                and {s.span_id for s in restored}
                == {s.span_id for s in spans}
                and all(s.trace_id == trace_id for s in restored))
        return {"slo_breached": breached,
                "breach_exemplars": len(breach_ex),
                "exemplar_trace_followable": followable,
                "perfetto_round_trips": round_trips}
    finally:
        server.stop()
        if not was_enabled:
            tracer.disable()


# --------------------------------------------------------------------- #


def run_checks(result):
    att = result["attribution"]
    ovh = result["overhead"]
    flz = result["fleet"]
    exm = result["exemplar"]
    return {
        "attribution_sum_shared": att["shared"]["attribution_gap"] <= 0.05,
        "attribution_sum_serialized":
            att["serialized"]["attribution_gap"] <= 0.05,
        "all_tenants_attributed": (
            att["shared"]["all_tenants_attributed"]
            and att["serialized"]["all_tenants_attributed"]),
        "metering_overhead_le_1pct": ovh["overhead_frac"] <= 0.01,
        "fleetz_equals_replica_sum": flz["rollup_matches_direct_sum"],
        "federated_page_valid": flz["federated_page_valid"],
        "slo_breach_exemplar_followable": (
            exm["slo_breached"] and exm["breach_exemplars"] > 0
            and exm["exemplar_trace_followable"]),
        "perfetto_round_trips": exm["perfetto_round_trips"],
    }


def record(result, checks_ok, no_record=False):
    if no_record:
        return
    from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

    record_run(
        DEFAULT_HISTORY, "cost_attribution",
        config={"bursts": result["config"]["bursts"],
                "overhead_requests": result["config"]["overhead_requests"],
                "tenants": [name for name, _ in ROSTER]},
        metrics={"wall_s": result["wall_s"],
                 # the metering-overhead sentinel perf-gate watches: the
                 # metered arm's median request latency (a meter that
                 # got expensive moves it)
                 "metered_median_s": result["overhead"]["median_on_s"]},
        extra={"checks_ok": checks_ok,
               "attribution_gap_shared":
                   result["attribution"]["shared"]["attribution_gap"],
               "overhead_frac": result["overhead"]["overhead_frac"]})


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--check", action="store_true",
                        help="exit nonzero unless every criterion holds")
    parser.add_argument("--bursts", type=int, default=24)
    parser.add_argument("--overhead-requests", type=int, default=400,
                        help="requests per overhead arm (per-request "
                             "on/off alternation on one server)")
    parser.add_argument("--no-record", action="store_true",
                        help="skip the perf-history self-record")
    args = parser.parse_args()

    t0 = time.monotonic()
    clock = DispatchClock()
    result = {"config": {"bursts": args.bursts,
                         "overhead_requests": args.overhead_requests}}
    result["attribution"] = run_attribution_arm(clock, bursts=args.bursts)
    result["overhead"] = run_overhead_arm(
        clock, requests=args.overhead_requests)
    result["fleet"] = run_fleet_arm(clock)
    result["exemplar"] = run_exemplar_arm(clock)
    result["wall_s"] = round(time.monotonic() - t0, 2)
    checks = run_checks(result)
    result["checks"] = checks
    checks_ok = all(checks.values())
    result["checks_ok"] = checks_ok
    record(result, checks_ok, no_record=args.no_record)
    print(json.dumps(result))
    if args.check and not checks_ok:
        failed = [k for k, v in checks.items() if not v]
        print(f"cost_attribution_bench: FAILED {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
