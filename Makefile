# Common workflows. Cluster deployment targets live in cluster/Makefile.{pool,serve};
# docker image targets in dockerfiles/Makefile.

PY ?= python

.PHONY: test tier1 collect fuzz bench configs serve sweep-pool sweep-serve analysis multihost-ci sched-bench chaos-bench lint obs-check health-check perf-gate warmup-bench stream-bench exact-bench autoscale-bench accuracy-gate tenant-bench deepshap-bench cost-bench anytime-bench profile-bench quality-bench pod-bench

lint:            ## unified static gate: dks-analyze (concurrency + JAX-contract + serving-ladder lints, scripts/dks_lint.py) + obs-check + health-check behind ONE exit code; <60s budget self-asserted
	env JAX_PLATFORMS=cpu $(PY) scripts/dks_lint.py --check

multihost-ci:    ## multi-host validation: 2-proc pool/phi/interactions, 4-proc 2x2 mesh, 2-proc serve (one JSON line, rc 0/1)
	$(PY) benchmarks/multihost_ci.py

test: lint       ## full suite on CPU with 8 virtual devices (gated on `make lint`)
	env PYTHONPATH= JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q

tier1: SHELL := /bin/bash
tier1:           ## the ROADMAP tier-1 verify command, verbatim (PIPESTATUS needs bash)
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

collect:         ## fast collection smoke: a conftest/import regression fails here in seconds, not behind the 870s tier-1 budget
	env PYTHONPATH= JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q --collect-only -p no:cacheprovider

sched-bench:     ## scheduling A/B: SLO scheduler + cache vs FIFO under open-loop overload (one JSON line, exits nonzero on criteria fail)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/scheduling_bench.py --check

chaos-bench:     ## chaos scenario: kill-one-replica + slow-replica serving (zero lost/dup) and killed-then-resumed pool run (<=1 shard recompute, bit-identical)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/chaos_bench.py --check

warmup-bench:    ## cold-start A/B: persistent compile cache across process starts (zero fresh ladder compiles on warm start) + plan-constant cache on small-B requests, phi bit-identity asserted
	env JAX_PLATFORMS=cpu $(PY) benchmarks/warmup_bench.py --check

stream-bench:    ## streaming hot path A/B: binary wire + staging vs JSON on the REAL linear engine at B=1 (>=2x goodput, phi bit-identical, device-busy fraction reported)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/streaming_bench.py --check

exact-bench:     ## exact-TreeSHAP arms: packed path-parallel schedule vs einsum vs sampled at >=1000 trees x depth>=10 (phi bit-identical), plus exact requests on the staged+donated serving hot path; self-records for perf-gate
	env JAX_PLATFORMS=cpu $(PY) benchmarks/exact_ab.py --arm large,serving --check

autoscale-bench: ## elastic-fleet A/B: diurnal open-loop replay, autoscaled min=1..max=3 fleet vs static fleets (holds p99 SLO at >=30% fewer replica-seconds; scale-up first answer <=5s via the warmup ladder; drains lose/duplicate nothing)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/autoscale_bench.py --check

tenant-bench:    ## multi-tenant gateway: 3 families served concurrently (phi bit-identical to dedicated), hot-swap mid-run, noisy-tenant quota isolation, PLUS the cross-tenant batching sweep (1->8 mixed-path tenants >=85% of the single-tenant ceiling, shared-program parity); self-records for perf-gate
	env JAX_PLATFORMS=cpu $(PY) benchmarks/multitenant_bench.py --arm all --check

cost-bench:      ## tenant cost attribution: per-tenant device-seconds sum to the directly-measured dispatch total (shared AND serialized batching), metering overhead <=1%, /fleetz == sum of per-replica scrapes, SLO-breach exemplar -> Perfetto; self-records for perf-gate
	env JAX_PLATFORMS=cpu $(PY) benchmarks/cost_attribution_bench.py --check

profile-bench:   ## continuous profiling + memory ledger: sampler on/off median overhead <=1% (per-request alternation), ledger total == independent cache walk, pressure drill evicts with bit-identical answers, hot-role samples land on engine frames, proxy /profilez?federate=1 == per-replica fold; self-records for perf-gate
	env JAX_PLATFORMS=cpu $(PY) benchmarks/profile_bench.py --check

quality-bench:   ## continuous correctness: injected engine.phi corruption flagged within K requests (zero false positives clean), audit on/off median overhead <=1%, shadow oracle trips its device-seconds budget (meter within budget + one run), canary verdicts ok/drift across gated hot swaps; self-records for perf-gate
	env JAX_PLATFORMS=cpu $(PY) benchmarks/quality_bench.py --check

pod-bench:       ## pod serving fabric on a 2-process gloo CPU mesh: phi bit-identical to single-process serving, bucketed broadcasts smaller than full-slot at B=1, pipelined goodput >= 1.3x lock-step, drain loses/duplicates nothing, pod device-seconds within 5% of the per-process clock sum; self-records for perf-gate
	env JAX_PLATFORMS=cpu $(PY) benchmarks/pod_serve_bench.py --check

anytime-bench:   ## anytime refinement: resumed round-k phi bit-identical to from-scratch, reported error bounds true error within x2 at >=90% of rounds, overload A/B where the anytime arm answers every admitted request by deadline (monotone streamed error) while the fixed-nsamples control sheds or blows p99; self-records for perf-gate
	env JAX_PLATFORMS=cpu $(PY) benchmarks/anytime_bench.py --check

obs-check:       ## observability drift lint: registry vs docs/OBSERVABILITY.md catalog, stray dks_ literals, ad-hoc exposition renderers
	env JAX_PLATFORMS=cpu $(PY) scripts/obs_check.py

health-check:    ## alert-engine golden test: replay the committed SLO fixture, assert pending->firing->resolved at the golden timestamps
	env JAX_PLATFORMS=cpu $(PY) scripts/health_check.py

perf-gate:       ## perf-regression gate: newest recorded benchmark runs vs their trailing same-config baselines (results/perf_history.jsonl)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/regression_gate.py --check

accuracy-gate:   ## estimator-accuracy gate: sampled estimator swept vs exact-TN/exact-tree/DeepSHAP ground truth across nsamples budgets; gates error regressions like perf-gate gates wall time (results/accuracy_history.jsonl)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/estimator_accuracy.py --check

deepshap-bench:  ## deep-model attribution: DeepSHAP vs brute-force exact Shapley on piecewise-linear nets, certified matched-error >=10x speedup vs the sampled estimator, CNN image tenant served end-to-end over the binary wire at interactive SLO; self-records for perf-gate
	env JAX_PLATFORMS=cpu $(PY) benchmarks/deepshap_bench.py --check

fuzz:            ## 3x fresh-seed hypothesis property sweeps (new examples per run)
	for i in 1 2 3; do \
	  env PYTHONPATH= JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_properties.py -q -p no:cacheprovider || exit 1; \
	done

bench:           ## headline benchmark (one JSON line, runs on the attached chip)
	$(PY) bench.py

configs:         ## full BASELINE.json configuration suite
	$(PY) benchmarks/configs.py --config all

serve:           ## serve the default Adult explainer on :8000
	$(PY) -m distributedkernelshap_tpu.serving.main

sweep-pool:      ## device-sweep pool benchmark (reference ray_pool.py analog)
	$(PY) benchmarks/pool.py -benchmark 1 -w 8 -b 320 -n 3

sweep-serve:     ## serving sweep (reference serve_explanations.py analog)
	$(PY) benchmarks/serve_explanations.py --replicas 8 -b 1 5 10 -n 1

analysis:        ## aggregate result pickles and plot
	$(PY) benchmarks/analysis.py --results results --plot results/scaling.png \
		--compare images/comparison_tpu_vs_reference.png
