"""Headline benchmark: explain 2560 Adult instances, bg=100, link='logit'.

The reference's benchmark task (``benchmarks/ray_pool.py:82-110``,
``README.md:3``): sequential baseline 1736.89 s, best 32-vCPU Ray actor-pool
time 125.05 s (BASELINE.md).  This script runs the same task end-to-end on
the attached TPU device(s) and prints ONE JSON line:

    {"metric": "adult_2560_bg100_wall_s", "value": <seconds>, "unit": "s",
     "vs_baseline": <125.05 / seconds>}

``vs_baseline`` is the speed-up over the reference's best single-node
(32-vCPU) actor-pool configuration.  Timing excludes compilation (one warmup
run, like the reference's multi-run protocol that reuses fitted explainers)
and includes host->device transfer of the batch + full retrieval of the
Explanation payload.
"""

import json
import sys
import time

import numpy as np

RAY_POOL_32VCPU_BASELINE_S = 125.05  # BASELINE.md: best single-node reference


def main() -> int:
    import jax

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.utils import load_data, load_model

    data = load_data()
    clf = load_model()
    group_names, groups = data["all"]["group_names"], data["all"]["groups"]
    X_explain = np.ascontiguousarray(
        data["all"]["X"]["processed"]["test"].toarray(), dtype=np.float32)
    background = data["background"]["X"]["preprocessed"]
    assert X_explain.shape[0] == 2560, X_explain.shape
    assert background.shape[0] == 100, background.shape

    n_devices = len(jax.devices())
    distributed_opts = {"n_devices": n_devices} if n_devices > 1 else None

    explainer = KernelShap(clf.predict_proba, link="logit",
                           feature_names=group_names, seed=0,
                           distributed_opts=distributed_opts)
    explainer.fit(background, group_names=group_names, groups=groups)

    # warmup: compile + first run (the reference also reuses a fitted
    # explainer across its nruns timing loop, ray_pool.py:70-79)
    explainer.explain(X_explain, silent=True)

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        explanation = explainer.explain(X_explain, silent=True)
        times.append(time.perf_counter() - t0)

    # sanity: additivity of the produced explanation
    sv = explanation.shap_values
    total = np.stack(sv, 1).sum(-1) + np.asarray(explanation.expected_value)[None, :]
    err = float(np.abs(total - explanation.data["raw"]["raw_prediction"]).max())
    if not err < 1e-3:
        print(json.dumps({"error": f"additivity violated: {err}"}))
        return 1

    value = float(np.median(times))
    print(json.dumps({
        "metric": "adult_2560_bg100_wall_s",
        "value": round(value, 4),
        "unit": "s",
        "vs_baseline": round(RAY_POOL_32VCPU_BASELINE_S / value, 1),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
