"""Headline benchmark: explain 2560 Adult instances, bg=100, link='logit'.

The reference's benchmark task (``benchmarks/ray_pool.py:82-110``,
``README.md:3``): sequential baseline 1736.89 s, best 32-vCPU Ray actor-pool
time 125.05 s (BASELINE.md).  This script runs the same task end-to-end on
the attached TPU device(s) and prints ONE JSON line:

    {"metric": "adult_2560_bg100_wall_s", "value": <seconds>, "unit": "s",
     "vs_baseline": <125.05 / seconds>}

``vs_baseline`` is the speed-up over the reference's best single-node
(32-vCPU) actor-pool configuration.  Timing excludes compilation (one warmup
run, like the reference's multi-run protocol that reuses fitted explainers)
and includes host->device transfer of the batch + full retrieval of the
Explanation payload.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

RAY_POOL_32VCPU_BASELINE_S = 125.05  # BASELINE.md: best single-node reference


def _device_reachable(timeout_s: float = None):
    """Probe backend init in a subprocess; returns ``(ok, detail)``.

    A killed TPU client can wedge the tunnel relay so that backend init
    blocks forever (uninterruptibly, in C) for every later process. Probing
    in a throwaway subprocess lets this benchmark fail fast with a
    parseable error line instead of hanging the driver. The probe child is
    abandoned (not waited on indefinitely) if it survives SIGKILL — a child
    stuck in an uninterruptible syscall would otherwise re-hang us here.

    The timeout matches SKILL.md's full-patience rule (590s): right after a
    wedge clears, the first backend init can take minutes, and killing a
    client mid-grant re-wedges the relay — only a full-patience hang may be
    treated as "wedged" (at which point the child holds no grant and
    terminating it is safe). The healthy path pays backend init twice
    (probe + run); that cost is accepted to keep the driver hang-proof.
    """

    if timeout_s is None:
        timeout_s = float(os.environ.get("DKS_BENCH_PROBE_TIMEOUT", "590"))
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    try:
        _, err = proc.communicate(timeout=timeout_s)
        if proc.returncode == 0:
            return True, ""
        return False, err.decode(errors="replace").strip()[-400:]
    except subprocess.TimeoutExpired:
        proc.terminate()  # SIGTERM first: mirrors how a shell timeout ends it
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.communicate(timeout=5)
            except subprocess.TimeoutExpired:
                pass  # unkillable child: leave it behind rather than hang
        return False, f"backend init did not complete within {timeout_s:.0f}s"


def main() -> int:
    if os.environ.get("DKS_BENCH_SKIP_PROBE") != "1":
        # a wedged relay can recover on a multi-minute timescale; retry the
        # probe (sequentially — one prober at a time) before giving up so a
        # transient wedge doesn't turn into a recorded bench failure
        attempts = max(1, int(os.environ.get("DKS_BENCH_PROBE_RETRIES", "2")) + 1)
        retry_delay = float(os.environ.get("DKS_BENCH_PROBE_RETRY_DELAY", "120"))
        for attempt in range(attempts):
            ok, detail = _device_reachable()
            # only timeout-type failures are the transient "wedged relay"
            # case worth retrying; a probe that exits fast failed permanently
            if ok or not detail.startswith("backend init did not complete"):
                break
            if attempt < attempts - 1:
                time.sleep(retry_delay)
        if not ok:
            print(json.dumps({
                "metric": "adult_2560_bg100_wall_s",
                "error": "device backend unreachable (tunnel relay wedged?); "
                         "see .claude/skills/verify/SKILL.md for recovery notes",
                "detail": detail,
            }))
            return 1

    import jax

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.utils import load_data, load_model

    data = load_data()
    clf = load_model()
    group_names, groups = data["all"]["group_names"], data["all"]["groups"]
    X_explain = np.ascontiguousarray(
        data["all"]["X"]["processed"]["test"].toarray(), dtype=np.float32)
    background = data["background"]["X"]["preprocessed"]
    assert X_explain.shape[0] == 2560, X_explain.shape
    assert background.shape[0] == 100, background.shape

    n_devices = len(jax.devices())
    distributed_opts = {"n_devices": n_devices} if n_devices > 1 else None

    explainer = KernelShap(clf.predict_proba, link="logit",
                           feature_names=group_names, seed=0,
                           distributed_opts=distributed_opts)
    explainer.fit(background, group_names=group_names, groups=groups)

    # warmup: compile + first run (the reference also reuses a fitted
    # explainer across its nruns timing loop, ray_pool.py:70-79)
    explainer.explain(X_explain, silent=True)

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        explanation = explainer.explain(X_explain, silent=True)
        times.append(time.perf_counter() - t0)

    # sanity: additivity of the produced explanation
    sv = explanation.shap_values
    total = np.stack(sv, 1).sum(-1) + np.asarray(explanation.expected_value)[None, :]
    err = float(np.abs(total - explanation.data["raw"]["raw_prediction"]).max())
    if not err < 1e-3:
        print(json.dumps({"error": f"additivity violated: {err}"}))
        return 1

    value = float(np.median(times))
    print(json.dumps({
        "metric": "adult_2560_bg100_wall_s",
        "value": round(value, 4),
        "unit": "s",
        "vs_baseline": round(RAY_POOL_32VCPU_BASELINE_S / value, 1),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
