"""Headline benchmark: explain 2560 Adult instances, bg=100, link='logit'.

The reference's benchmark task (``benchmarks/ray_pool.py:82-110``,
``README.md:3``): sequential baseline 1736.89 s, best 32-vCPU Ray actor-pool
time 125.05 s (BASELINE.md).  This script runs the same task end-to-end on
the attached TPU device(s) and prints ONE JSON line:

    {"metric": "adult_2560_bg100_wall_s", "value": <seconds>, "unit": "s",
     "vs_baseline": <125.05 / seconds>}

``vs_baseline`` is the speed-up over the reference's best single-node
(32-vCPU) actor-pool configuration.  Timing excludes compilation (one warmup
run, like the reference's multi-run protocol that reuses fitted explainers)
and includes host->device transfer of the batch + full retrieval of the
Explanation payload.

Budgeting: EVERYTHING here is bounded by ``DKS_BENCH_BUDGET`` seconds
(default 420) so an external driver always receives a parseable JSON line —
success or error — instead of killing an unresponsive process (round 1
recorded ``rc: 124`` with no output because the probe + retry budget
exceeded the driver's).  The budget splits into a backend probe phase (a
wedged TPU tunnel relay blocks backend init uninterruptibly; probing in a
throwaway child lets us fail fast — and retrying: a relay recovering from a
wedge can answer the second attempt, so the probe phase makes two bounded
attempts by default) and the benchmark run itself, which executes in a
child process killed at the remaining budget.  On this VM the healthy path
needs ~100-140 s total (data/assets cached, compile ~15-40 s), so the
default leaves ample margin.

When the device stays unreachable (or the run phase dies), a reserved tail
of the budget (``DKS_BENCH_FALLBACK_RESERVE``, 100 s cap — worst-case
wedged-path wall time stays inside a conservative 300 s driver timeout)
runs the SAME jitted pipeline on CPU in a child with the axon hook
stripped (``PYTHONPATH='' JAX_PLATFORMS=cpu`` — CPU-forced processes work
even under a relay wedge) and reports it as a clearly-labelled
``cpu_fallback_wall_s`` secondary field in the error JSON, so the driver
artifact always carries a real measurement without misrepresenting it as a
TPU number.

Retry horizon beyond one invocation: every on-chip success caches its
record to ``results/bench_last_success.json`` (the relay-recovery watcher
runs this benchmark the moment the chip answers), and the wedged-path
error JSON attaches that cache as ``last_onchip`` with its age — so ONE
healthy relay window anywhere in the round is enough for the driver
artifact to carry an on-chip number, clearly labelled as cached.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

RAY_POOL_32VCPU_BASELINE_S = 125.05  # BASELINE.md: best single-node reference

_METRIC = "adult_2560_bg100_wall_s"


def _wire_format_name() -> str:
    """The serving wire protocol this commit negotiates by default
    (``serving/wire.py``) — recorded so historical result lines state
    which protocol their era's serving stack spoke."""

    from distributedkernelshap_tpu.serving import wire

    return wire.WIRE_FORMAT_NAME


def _total_budget() -> float:
    return float(os.environ.get("DKS_BENCH_BUDGET", "420"))


def _phi_vs_exact_err(explainer, X, explanation, limit: int = 16):
    """Max abs phi error of the measured sampled run against the exact
    path on the first ``limit`` instances, when the fitted predictor
    admits one (lifted tree ensemble or tensor-train structure at
    identity link) — ``None`` otherwise (the headline Adult task runs a
    logit-link linear model, which has no exact route).  TPU reruns then
    carry accuracy alongside wall-clock and ``kernel_path``."""

    try:
        engine = explainer._explainer
        if getattr(engine, "_exact_flavor", lambda: None)() is None \
                or engine.config.link != "identity":
            return None
        exact = explainer.explain(X[:limit], silent=True,
                                  nsamples="exact").shap_values
        exact = exact if isinstance(exact, list) else [exact]
        sampled = explanation.shap_values
        sampled = sampled if isinstance(sampled, list) else [sampled]
        return round(float(max(
            np.abs(np.asarray(s)[:limit] - np.asarray(e)).max()
            for s, e in zip(sampled, exact))), 8)
    except Exception:
        return None  # accuracy is a bonus field, never a bench failure


def _device_probe(timeout_s: float):
    """Probe backend init in a subprocess; returns ``(ok, detail)``.

    Delegates to the shared ladder (``benchmarks/_evidence.device_probe``
    — one copy of the delicate kill-a-TPU-client-safely escalation for
    this benchmark and the recovery watcher).  The module-level indirection
    is load-bearing: the contract tests monkeypatch ``bench._device_probe``.
    """

    from benchmarks._evidence import device_probe

    return device_probe(timeout_s)


def run_benchmark(cpu_fallback: bool = False) -> int:
    """The actual benchmark (child-process entry: ``python bench.py --run``).

    ``cpu_fallback`` is the ``--run-cpu`` entry: same pipeline, run by a
    child whose env strips the axon hook and forces the CPU backend; its
    result is reported under a distinct metric name so it can never be
    mistaken for a TPU measurement.
    """

    import jax

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.runtime.compile_cache import (
        compile_events,
        enable_persistent_cache,
    )
    from distributedkernelshap_tpu.utils import load_data, load_model

    # compile accounting from the first fit compile on (registers the
    # jax.monitoring listener before anything traces), and the persistent
    # compile cache when DKS_COMPILE_CACHE_DIR is set — the result line
    # then records cache effectiveness alongside wall time
    enable_persistent_cache()
    compile_before = compile_events().snapshot()

    data = load_data()
    clf = load_model()
    group_names, groups = data["all"]["group_names"], data["all"]["groups"]
    X_explain = np.ascontiguousarray(
        data["all"]["X"]["processed"]["test"].toarray(), dtype=np.float32)
    background = data["background"]["X"]["preprocessed"]
    assert X_explain.shape[0] == 2560, X_explain.shape
    assert background.shape[0] == 100, background.shape

    n_devices = len(jax.devices())
    distributed_opts = {"n_devices": n_devices} if n_devices > 1 else None

    from distributedkernelshap_tpu.utils import data_provenance

    explainer = KernelShap(clf.predict_proba, link="logit",
                           feature_names=group_names, seed=0,
                           distributed_opts=distributed_opts)
    explainer.fit(background, group_names=group_names, groups=groups,
                  data_provenance=data_provenance(data))

    # warmup: compile + first run (the reference also reuses a fitted
    # explainer across its nruns timing loop, ray_pool.py:70-79)
    explainer.explain(X_explain, silent=True)

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        explanation = explainer.explain(X_explain, silent=True)
        times.append(time.perf_counter() - t0)

    # sanity: additivity of the produced explanation
    sv = explanation.shap_values
    total = np.stack(sv, 1).sum(-1) + np.asarray(explanation.expected_value)[None, :]
    err = float(np.abs(total - explanation.data["raw"]["raw_prediction"]).max())
    metric = _METRIC + ("_cpu_fallback" if cpu_fallback else "")
    if not err < 1e-3:
        print(json.dumps({"metric": metric,
                          "error": f"additivity violated: {err}"}))
        return 1

    value = float(np.median(times))
    record = {
        "metric": metric,
        "value": round(value, 4),
        "unit": "s",
        "vs_baseline": round(RAY_POOL_32VCPU_BASELINE_S / value, 1),
        # honest-labelling: 'tpu' through the axon tunnel, 'cpu' when no
        # accelerator backend was reachable (never silently conflated)
        "platform": jax.default_backend(),
        # 'uci' (real fetch) | 'synthetic' (offline lookalike) —
        # measurements always declare which data they ran on
        "data_provenance": explanation.meta.get("data_provenance",
                                                "unspecified"),
        # which evaluation kernel engaged + Pallas degrade count — a Mosaic
        # auto-degrade must never masquerade as a kernel measurement
        "kernel_path": explainer.kernel_path,
        # protocol in effect for serving deployments at this commit (this
        # bench itself explains in-process; the field pins which wire
        # format a TPU rerun's serving numbers would ride — ROADMAP bench
        # caveat) + the headline task's goodput in rows/s, the unit the
        # streaming bench gates on
        "wire_format": _wire_format_name(),
        "goodput_rows_per_s": round(X_explain.shape[0] / value, 1),
        # model attribution (multi-tenant era): which registered model
        # identity this measurement belongs to, so perf-history entries
        # from multi-model fleets stay attributable per tenant
        "model_id": "adult_lr",
        "model_version": 1,
        # pod-fabric era: how many host processes this measurement's mesh
        # spanned (1 = single-process; a TPU pod rerun records its true
        # size so per-host and per-pod numbers never get conflated)
        "pod_processes": jax.process_count(),
    }
    # compile accounting for the whole run (fit + warmup + timed loop):
    # fresh = XLA compiled, cache_hit = the persistent compile cache
    # served the executable (non-zero only with DKS_COMPILE_CACHE_DIR) —
    # BENCH_*.json then records cache effectiveness alongside wall time.
    # Snapshot BEFORE the accuracy probe: its exact-path rerun compiles a
    # program the measured sampled run never touched
    compile_delta = compile_events().delta(compile_before,
                                           compile_events().snapshot())
    # max abs phi error vs the exact path (tree/TN predictors at
    # identity link; null when no exact route exists for the task)
    record["phi_vs_exact_err"] = _phi_vs_exact_err(explainer, X_explain,
                                                   explanation)
    # the serving-side invariant screen (observability/quality.py) run
    # over this bench's final explanation: a TPU rerun carries a
    # correctness verdict next to its wall time, not just a speed
    from distributedkernelshap_tpu.observability.quality import (
        screen_arrays,
    )

    record["audit_violations"] = len(screen_arrays(
        sv, explanation.expected_value,
        explanation.data["raw"]["raw_prediction"], path="sampled"))
    record["compile_total"] = {
        k: int(v) for k, v in compile_delta["totals"].items()}
    record["compile_seconds_total"] = {
        k: round(v, 3) for k, v in compile_delta["seconds_totals"].items()}
    print(json.dumps(record))
    if not cpu_fallback:
        # persist the on-chip success for the wedged-path error JSON: the
        # shared cache (benchmarks/_evidence.py) is fed by EVERY protocol
        # that measures this task on chip, so ONE healthy window anywhere in
        # the round puts an on-chip number (clearly labelled as cached) into
        # the driver artifact.  record_onchip_success refuses platform=cpu.
        from benchmarks._evidence import record_onchip_success

        record_onchip_success(record, protocol="bench.py")
    return 0


def _cpu_fallback(timeout_s: float):
    """Run the same pipeline CPU-only in a child; returns the measured
    wall-clock (or an error string).

    The child strips ``PYTHONPATH`` so the axon sitecustomize hook never
    loads (a wedged relay blocks axon *backend init*, not CPU work) and
    forces ``JAX_PLATFORMS=cpu`` — the one combination verified to run
    reliably under a relay wedge.
    """

    if timeout_s < 30:
        return None, "no budget left for the CPU fallback"
    # DKS_OFFLINE: the fallback's budget must never be spent on network
    # attempts if the data caches are somehow missing
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu", DKS_OFFLINE="1")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--run-cpu"],
        stdout=subprocess.PIPE, cwd=os.path.dirname(os.path.abspath(__file__)),
        env=env)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.communicate(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        return None, f"cpu fallback exceeded {timeout_s:.0f}s"
    try:
        last = out.decode().strip().splitlines()[-1]
        rec = json.loads(last)
        if not isinstance(rec, dict):  # a bare number/list is not a result
            raise ValueError(last)
    except (IndexError, ValueError):
        return None, f"cpu fallback exited rc={proc.returncode} without JSON"
    if "value" in rec:
        try:
            return float(rec["value"]), None
        except (TypeError, ValueError):
            return None, f"cpu fallback JSON had a non-numeric value: {rec['value']!r}"
    return None, rec.get("error", "cpu fallback returned no value")


def _emit_error(payload: dict, t_start: float, budget: float,
                reserve: float) -> int:
    """Print the error JSON, augmented with a clearly-labelled CPU-fallback
    measurement when the remaining budget allows — the driver artifact then
    always carries a number, without misrepresenting it as a TPU result.

    The fallback is capped at ``reserve`` (main()'s clamped
    ``DKS_BENCH_FALLBACK_RESERVE``, not the whole remaining budget): total
    wall time on the wedged path must stay well inside a conservative 300 s
    driver timeout, not merely inside ``DKS_BENCH_BUDGET``.
    """

    remaining = min(budget - (time.monotonic() - t_start) - 10.0, reserve)
    value, err = _cpu_fallback(remaining)
    if value is not None:
        payload["cpu_fallback_wall_s"] = value
        payload["cpu_fallback_note"] = (
            "same jitted pipeline, CPU backend, ONE core — NOT a TPU "
            f"measurement (reference 32-vCPU pool best: "
            f"{RAY_POOL_32VCPU_BASELINE_S} s)")
    elif err:
        payload["cpu_fallback_error"] = err
    # widen the effective retry horizon beyond this single invocation
    # (VERDICT r3 #1, r4 #1): if any session this round captured an on-chip
    # run under ANY protocol (bench.py, tpu_revalidate's adult config, the
    # pool point, the recovery watcher — all feed benchmarks/_evidence.py),
    # attach it — clearly labelled as cached, never as this invocation's
    # measurement.
    try:
        from benchmarks._evidence import load_last_onchip

        last = load_last_onchip()
        if last is not None:
            payload["last_onchip"] = last
    except Exception:
        pass  # evidence attachment must never break the error contract
    print(json.dumps(payload))
    return 1


def main() -> int:
    if "--run-cpu" in sys.argv:
        return run_benchmark(cpu_fallback=True)
    if "--run" in sys.argv:
        return run_benchmark()

    t_start = time.monotonic()
    budget = _total_budget()

    # the CPU fallback needs ~60-90 s (imports + compile + 3 timed runs);
    # reserving it inside the budget keeps the hard bound: probe + fallback
    # (wedged path) or probe + run (healthy path) both resolve within
    # DKS_BENCH_BUDGET.  Worst-case wedged-path latency with the default
    # budget: ~145 s probe + ~100 s fallback ≈ 250 s — still inside a
    # conservative 300 s driver timeout.
    fallback_reserve = min(
        float(os.environ.get("DKS_BENCH_FALLBACK_RESERVE", "100")),
        0.3 * budget)

    if os.environ.get("DKS_BENCH_SKIP_PROBE") != "1":
        # probe phase: at most ~35% of the budget across all attempts, so
        # the run phase (or the CPU fallback) always keeps enough time to
        # finish (a cached-compile TPU run needs well under a minute; the
        # first-ever compile ~40 s).  Two attempts by default: a relay
        # recovering from a wedge often answers a later attempt (the wedge
        # clears asynchronously), and a healthy backend answers the first
        # attempt in <1 s either way.
        attempts = max(1, int(os.environ.get("DKS_BENCH_PROBE_RETRIES", "1")) + 1)
        retry_delay = float(os.environ.get("DKS_BENCH_PROBE_RETRY_DELAY", "20"))
        probe_timeout = float(os.environ.get(
            "DKS_BENCH_PROBE_TIMEOUT",
            max(30.0, (0.35 * budget - (attempts - 1) * retry_delay) / attempts)))
        ok, detail = False, ""
        for attempt in range(attempts):
            ok, detail = _device_probe(probe_timeout)
            # only timeout-type failures are the transient "wedged relay"
            # case worth retrying; a probe that exits fast failed permanently
            if ok or not detail.startswith("backend init did not complete"):
                break
            if attempt < attempts - 1:
                time.sleep(retry_delay)
        if not ok:
            return _emit_error({
                "metric": _METRIC,
                "error": "device backend unreachable (tunnel relay wedged?); "
                         "see .claude/skills/verify/SKILL.md for recovery notes",
                "detail": detail,
            }, t_start, budget, fallback_reserve)

    # run phase in a child, bounded by what's left after reserving the
    # fallback tail (even if the probe succeeded and the device wedges
    # mid-run, the driver still gets a JSON line instead of rc=124).  If the
    # probe somehow consumed nearly everything, fail with a JSON line
    # immediately rather than over-running the budget.
    #
    # DKS_BENCH_DEADLINE additionally bounds when the LAST line prints on
    # the worst path (run hangs -> kill escalation -> CPU fallback): the
    # run timeout is clamped so run + fallback still land inside it.  A
    # healthy first-ever-compile TPU run needs ~140 s, comfortably under
    # the ~160 s this leaves with the defaults.
    deadline = float(os.environ.get("DKS_BENCH_DEADLINE", "280"))
    left = budget - (time.monotonic() - t_start) - 5.0
    if left <= 30:
        # still goes through _emit_error: the fallback will refuse for lack
        # of budget, but a cached on-chip record still reaches the artifact
        return _emit_error({"metric": _METRIC,
                            "error": "probe phase consumed the whole budget"},
                           t_start, budget, fallback_reserve)
    # forgo the fallback reserve rather than squeeze the run below a useful
    # bound (the run itself is the better artifact when it completes)
    remaining = left - fallback_reserve if left - fallback_reserve >= 60 else left
    until_deadline = (deadline - (time.monotonic() - t_start)
                      - fallback_reserve - 20.0)  # kill escalation margin
    if until_deadline < 60:
        # a slow probe path ate the deadline: a <60 s run slot can't fit
        # even a cached-compile TPU run, and silently dropping the clamp
        # (the round-3 behaviour) could overrun the stated deadline by
        # run + fallback.  Skip the run phase; the labelled CPU fallback
        # inside the reserve is the best artifact the deadline still allows.
        return _emit_error({
            "metric": _METRIC,
            "error": "probe phase left too little time before "
                     "DKS_BENCH_DEADLINE for a device run",
        }, t_start, budget, fallback_reserve)
    remaining = min(remaining, until_deadline)
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__), "--run"],
                            stdout=subprocess.PIPE)
    try:
        out, _ = proc.communicate(timeout=remaining)
        text = out.decode()
        # the contract is ONE parseable JSON line, even when the child dies
        # without printing (uncaught exception, OOM kill, signal)
        last = text.strip().splitlines()[-1] if text.strip() else ""
        try:
            json.loads(last)
        except ValueError:
            return _emit_error({
                "metric": _METRIC,
                "error": f"benchmark child exited rc={proc.returncode} "
                         f"without a JSON result",
                "detail": last[-400:],
            }, t_start, budget, fallback_reserve)
        sys.stdout.write(text)
        return proc.returncode
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.communicate(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        return _emit_error({
            "metric": _METRIC,
            "error": f"benchmark run exceeded the remaining budget "
                     f"({remaining:.0f}s of DKS_BENCH_BUDGET="
                     f"{budget:.0f}s); device hang mid-run?",
        }, t_start, budget, fallback_reserve)


if __name__ == "__main__":
    sys.exit(main())
