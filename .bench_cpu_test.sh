JAX_PLATFORMS=cpu python benchmarks/pool.py -b 32 -w 4 -n 1 2>&1 | tail -3
ls results/
