#!/bin/bash
# Relay-recovery watcher (round 2, post-fix): one prober, full 590 s
# patience, 300 s between probes. On recovery: re-run the two configs whose
# oracle changed (adult headline refresh + model_zoo with the f32-cast
# model_err fix), then verify the bench.py driver contract. All output to
# .tpu_watch2.log; single-shot — exits after the recovery work.
cd /root/repo
while true; do
  echo "[$(date +%H:%M:%S)] probe" >> .tpu_watch2.log
  if timeout 590 python -c "import jax; jax.devices()" >> .tpu_watch2.log 2>&1; then
    echo "[$(date +%H:%M:%S)] RECOVERED" >> .tpu_watch2.log
    sleep 30   # give any blocked-mid-RPC client a moment to resume/finish
    python benchmarks/tpu_revalidate.py \
      --skip mnist,covertype,adult_blackbox,serve,pool,regression \
      >> .tpu_watch2.log 2>&1
    DKS_BENCH_SKIP_PROBE=1 DKS_BENCH_BUDGET=420 python bench.py \
      >> .tpu_watch2.log 2>&1
    echo "[$(date +%H:%M:%S)] recovery work done" >> .tpu_watch2.log
    exit 0
  fi
  echo "[$(date +%H:%M:%S)] still wedged" >> .tpu_watch2.log
  sleep 300
done
